"""Multi-replica serving router: least-loaded proxying over a pool.

The front tier over :class:`~paddle_tpu.serving.pool.ReplicaPool`: one
stdlib HTTP server that proxies ``/v1/models/<name>:predict`` and
``:generate`` to the least-loaded healthy replica, so N single-process
``serve`` workers look like one service that survives crashes and hot
reloads (the reference's Go master/pserver fleet posture, rebuilt over
the PR-4/PR-9 serving stack). Four mechanisms:

**Load scoring.** A background poller GETs every replica's ``/statz``
(and ``/healthz``) each ``route_poll_ms``. A replica's score is::

    score = pending                          # micro-batch queue depth
          + sum(queued + running)            # generation backlog
          + 4.0 * sum(page_utilization)      # KV pressure, per engine
          + inflight                         # router-tracked, live

``inflight`` is the router's own count of proxied requests outstanding
at that replica — it moves between polls, so two requests arriving
1 ms apart spread out instead of both chasing the same stale snapshot.
The KV term weights a nearly-full page pool like a 4-deep queue:
exhaustion there sheds (429), which is strictly worse than queueing.

**Health.** ``/healthz`` failures eject a replica from routing after
``route_eject_after`` consecutive misses; an ejected replica is still
polled, and readmits only after ``route_readmit_after`` consecutive
successes (probation — one lucky poll must not put a flapping replica
back in rotation). A replica the pool restarted (its generation
changed) starts with a clean health record.

**Failover.** A proxy failure (connection refused/reset mid-flood —
the SIGKILLed-replica case) or an exhaustion answer (429/503) retries
ONCE against the next-best replica, with the first excluded. The retry
is recorded (``route_failover``); a second failure returns the last
honest answer (the replica's own 429 with its Retry-After) or 502.
The proxy edge is fault site ``serving.route``: an armed raise is
indistinguishable from a dead replica — degrade to failover, never a
router crash. When no healthy replica exists the router sheds with 503
+ ``Retry-After`` instead of hanging.

**Gray failures.** Binary health misses a replica that answers every
/healthz but runs 5x slower than its peers (thermal throttle, bad
host, flaky NIC). With ``route_gray_ratio`` > 0 the poller feeds each
replica's proxied-latency EWMA into the ONE
:class:`~paddle_tpu.resilience.grayfail.SkewDetector` shared with the
elastic supervisor (robust median+MAD baseline, consecutive-breach
streaks, hysteresis); a CONDEMNED replica is drained and ejected into
the same probation cycle as a health-failing one — even though its
/healthz is 200 — and held out (``route_gray_hold_s``) before the
normal readmit probation may return it, its detector record forgotten
so a recovered replica starts clean and a still-slow one is simply
condemned again. Recorded as durable ``gray_suspected`` /
``gray_mitigated`` events; the last routable replica is never
gray-ejected (a slow answer beats no answer).

**Hedging.** With ``route_hedge_budget`` > 0, an IDEMPOTENT
``:predict`` proxy still unanswered past the router's observed p99
(floored at ``route_hedge_min_ms``) fires ONE hedged attempt at the
next-best replica; the first answer wins, the loser is discarded on
arrival. ``:generate`` is NEVER hedged — it consumes KV pages and
decode slots, and a duplicate generation is real double work, not a
cheap insurance read. The budget caps hedges as a fraction of proxied
traffic, so tail-chasing cannot melt an overloaded fleet; hedges and
wins are counted in /statz and the ``grayfail`` profiler family.

**Rolling reload.** ``:reload`` at the router fans out ONE replica at
a time: drain (stop routing new work to it), proxy the reload, then
gate on the reloaded replica passing ``/healthz`` before the next one
starts. A failed reload (the replica itself rolls back and answers
409) aborts the rollout, rolls any already-reloaded replicas back to
the artifact they were serving, and records ``reload_rollback`` — a
bad artifact can cost at most one replica's warm-up time, never the
fleet.

``RouterStats`` (the router's own ``/statz``) adds the autoscale
signal: per-model ``pressure = backlog / capacity + shed_rate``, where
backlog and capacity aggregate over healthy replicas (queued work vs.
``max_batch``/``max_running`` slots) and ``shed_rate`` is the shed
fraction since the previous poll. Sustained pressure > 1.0 means the
fleet is undersized; ~0 means it can shrink. Both the RAW per-poll
value and an EWMA-smoothed one (``FLAGS.route_pressure_alpha``) are
exposed — the closed-loop autoscaler
(:mod:`paddle_tpu.serving.autoscale`) acts only on the smoothed
signal, so one poll-window spike can neither trigger a scale-up nor
mask a real sustained overload.

**Membership.** The router registers with the pool's
``on_membership`` hook: a grow, shrink, restart respawn, or lost slot
wakes the poller immediately, so new and drained replicas are picked
up mid-flight instead of at the next timer tick. Rolling reload and
the autoscaler's membership mutations serialize on the pool's ONE
``membership_lock`` — a shrink can never land mid-rollout and a
rollout can never probe a replica the autoscaler just drained.

**Disaggregated tiers.** Replicas advertise their class through
``/statz`` (``tier``: ``""``/``prefill``/``decode``, the ``serve
--tier`` flag); the poller caches it per slot. When the routable fleet
holds BOTH classes, ``:generate`` becomes the two-hop disaggregated
path: hop 1 POSTs ``:prefill`` at the least-loaded prefill replica
(prompt pass only, answers the handoff artifact), hop 2 POSTs
``:decode`` at the least-loaded decode replica. The inter-tier hop is
fault site ``serving.ship``: a decode replica dying mid-handoff (or
the armed fault) records ``handoff_failed`` and RE-PREFILLS by routing
the original ``:generate`` to the decode tier — slower, bit-identical,
never lost. A one-tier (or untiered) fleet routes ``:generate``
single-hop exactly as before. 429 ``kv_pool_exhausted`` answers are
BACKPRESSURE, not failures: the replica that shed is held out of
``pick()`` for its own ``retry_after_ms`` hint, so the failover retry
and subsequent requests go to siblings with actual page inventory
instead of re-feeding the exhausted pool. ``tier_signal()`` gives the
per-tier autoscalers their class-correct signal: mean queue depth per
prefill replica (prefill load arrives as a queue), mean KV page-pool
occupancy per decode replica (decode capacity IS page inventory).
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..resilience import (fault_point, record_event,
                          record_durable_event)
from ..resilience.grayfail import (SkewDetector,
                                   SUSPECT as _SUSPECT,
                                   CONDEMNED as _CONDEMNED)
from .httpd import read_json_body, write_json_reply
from .service import _percentile
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["Router", "RouterStats", "make_router_server"]

# score weight of one fully-utilized KV page pool (see module docstring)
_KV_WEIGHT = 4.0


class _ReplicaState(object):
    """Router-side view of one pool slot: health record, last load
    snapshot, routing counters. Keyed by pool index; reset when the
    pool hands us a new generation for the slot."""

    __slots__ = ("index", "generation", "failures", "ok_streak", "ejected",
                 "statz", "statz_t", "score", "inflight", "routed",
                 "draining", "peak_load", "lat_ewma", "lat_n",
                 "gray_ejected", "gray_t", "tier", "backoff_until")

    def __init__(self, index, generation):
        self.index = index
        self.generation = generation
        self.failures = 0      # consecutive /healthz misses
        self.ok_streak = 0     # consecutive successes while ejected
        self.ejected = False
        self.statz = None
        self.statz_t = None
        self.score = 0.0       # statz-derived part (inflight added live)
        self.inflight = 0
        self.routed = 0
        self.draining = False  # rolling reload holds new work off
        self.peak_load = 0.0
        self.lat_ewma = None   # proxied-latency EWMA (gray signal), ms
        self.lat_n = 0         # proxied answers folded into the EWMA
        self.gray_ejected = False  # ejected on latency, /healthz still 200
        self.gray_t = None     # monotonic time of the gray ejection
        self.tier = None       # serving class from /statz; None = unknown
        self.backoff_until = 0.0   # kv_pool_exhausted hold (monotonic)


class Router(object):
    """Routing core: health/load poller + pick + proxy + rolling
    reload. HTTP-transport-only towards replicas (urllib against their
    ``serve`` endpoints); :func:`make_router_server` puts the front
    HTTP server over it.

    ``policy``: ``"least_loaded"`` (default) or ``"round_robin"`` (the
    load-bench baseline: health-aware, load-blind rotation).
    """

    def __init__(self, pool, policy="least_loaded", poll_ms=None,
                 eject_after=None, readmit_after=None,
                 proxy_timeout_s=None, pressure_alpha=None,
                 gray_ratio=None, gray_hold_s=None, hedge_budget=None,
                 hedge_min_ms=None, state_dir=None):
        from ..flags import FLAGS
        if policy not in ("least_loaded", "round_robin"):
            raise ValueError("policy must be least_loaded or round_robin, "
                             "got %r" % policy)
        self.pool = pool
        self.policy = policy
        self.poll_s = (poll_ms if poll_ms is not None
                       else FLAGS.route_poll_ms) / 1e3
        self.eject_after = int(eject_after if eject_after is not None
                               else FLAGS.route_eject_after)
        self.readmit_after = int(readmit_after if readmit_after is not None
                                 else FLAGS.route_readmit_after)
        self.proxy_timeout_s = float(
            proxy_timeout_s if proxy_timeout_s is not None
            else FLAGS.route_proxy_timeout_s)
        self.pressure_alpha = float(
            pressure_alpha if pressure_alpha is not None
            else FLAGS.route_pressure_alpha)
        if not 0.0 < self.pressure_alpha <= 1.0:
            raise ValueError("pressure_alpha must be in (0, 1], got %r"
                             % self.pressure_alpha)
        self.gray_ratio = float(gray_ratio if gray_ratio is not None
                                else FLAGS.route_gray_ratio)
        self.gray_hold_s = float(gray_hold_s if gray_hold_s is not None
                                 else FLAGS.route_gray_hold_s)
        self.hedge_budget = float(hedge_budget if hedge_budget is not None
                                  else FLAGS.route_hedge_budget)
        self.hedge_min_ms = float(hedge_min_ms if hedge_min_ms is not None
                                  else FLAGS.route_hedge_min_ms)
        # ONE skew detector (resilience.grayfail), shared judgement with
        # the elastic supervisor; policy (drain+eject into probation)
        # stays here. None = latency ejection off.
        self._gray = SkewDetector(ratio=self.gray_ratio) \
            if self.gray_ratio > 0 else None
        # where durable events land (route --state-dir); None degrades
        # record_durable_event to the in-memory record (or the
        # PADDLE_TPU_ELASTIC_STATE env default)
        self.state_dir = state_dir
        self._lock = _locks.make_lock("serving.router.state")
        self._states = {}            # pool index -> _ReplicaState
        self._counts = {}            # router-level counters
        self._latency_ms = []        # bounded: recent proxied latencies
        self._prev_model_counts = {} # model -> (requests, sheds) last poll
        self._pressure = {}          # model -> latest RAW pressure
        self._pressure_ewma = {}     # model -> EWMA-smoothed pressure
        self._rr_next = 0
        # membership mutation (rolling reload here, grow/shrink in the
        # autoscaler) serializes on the POOL's one lock
        self._membership_lock = getattr(pool, "membership_lock", None)
        if self._membership_lock is None:
            self._membership_lock = _locks.make_rlock(
                "serving.pool.membership")
        self._poller = None
        self._poll_wake = threading.Event()
        self._probe_exec = None
        self._hedge_exec = None
        self._closed = False
        self.autoscaler = None       # attached by serving.autoscale
        register = getattr(pool, "on_membership", None)
        if register is not None:
            register(self.notify_membership)

    def _probe_pool(self):
        """Reused executor for the concurrent health/load probes — a
        100 ms poll over N replicas must not churn N fresh threads per
        sweep for the life of the router (probes are I/O bound and
        their urllib timeouts bound a hung worker at ~4 s)."""
        with self._lock:
            if self._probe_exec is None:
                from concurrent.futures import ThreadPoolExecutor
                self._probe_exec = ThreadPoolExecutor(
                    max_workers=16,
                    thread_name_prefix="paddle_tpu-router-probe")
            return self._probe_exec

    def _hedge_pool(self):
        """Separate executor for hedged :predict attempts — a slow
        proxied request (bounded only by proxy_timeout_s) must not
        starve the health probes the ejection machinery runs on."""
        with self._lock:
            if self._hedge_exec is None:
                from concurrent.futures import ThreadPoolExecutor
                self._hedge_exec = ThreadPoolExecutor(
                    max_workers=16,
                    thread_name_prefix="paddle_tpu-router-hedge")
            return self._hedge_exec

    # -- counters ------------------------------------------------------------
    def _count(self, key, n=1):
        from .. import profiler as _prof
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
        _prof.update_router_counters(**{key: n})

    def _record(self, kind, **info):
        """Record one router event durably when a ``--state-dir`` was
        wired (ejections, failovers, reload rollbacks must survive a
        router crash — the trainer got events.jsonl in the elastic
        state dir, this is the serving tier's same trail); without one
        this is exactly ``record_event``."""
        return record_durable_event(kind, site="serving.route",
                                    state_dir=self.state_dir, **info)

    # -- transport -----------------------------------------------------------
    @staticmethod
    def _get_json(url, timeout):
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")

    @staticmethod
    def _post_json(url, payload, timeout):
        """POST; returns (status, body_dict, headers_dict). Non-2xx HTTP
        answers are ANSWERS (the replica spoke), returned not raised;
        only transport failures (refused/reset/timeout) propagate."""
        data = json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return (resp.status,
                        json.loads(resp.read() or b"{}"),
                        dict(resp.headers))
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                body = json.loads(raw or b"{}")
            except ValueError:
                body = {"error": raw.decode("utf-8", "replace"),
                        "kind": "upstream"}
            return e.code, body, dict(e.headers or {})

    # -- scoring -------------------------------------------------------------
    @staticmethod
    def statz_load(statz):
        """Load score from one replica's /statz snapshot (the formula in
        the module docstring; inflight is added by the picker).
        ``page_utilization`` comes as the PagePool dict ({frac: ...})
        from a real /statz and as a bare fraction from the /healthz
        readiness detail — accept both."""
        load = float(statz.get("pending", 0))
        for gen in (statz.get("generation") or {}).values():
            load += float(gen.get("queued", 0)) + float(gen.get("running",
                                                                0))
            pu = gen.get("page_utilization", 0.0)
            if isinstance(pu, dict):
                pu = pu.get("frac", 0.0)
            load += _KV_WEIGHT * float(pu)
        return load

    # -- polling -------------------------------------------------------------
    def _state_for(self, rep):
        """Find-or-make the state for a pool slot, resetting it when the
        pool respawned the process (generation bump). The HEALTH record
        resets with the process — a fresh worker must not inherit its
        predecessor's eject record — but ``draining`` is a SLOT-level
        policy mark (an autoscaler drain or a rolling reload in
        progress): a victim that crashes and respawns mid-drain must
        not silently re-enter rotation while its shrink proceeds."""
        st = self._states.get(rep.index)
        if st is None or st.generation != rep.generation:
            fresh = _ReplicaState(rep.index, rep.generation)
            if st is not None:
                fresh.draining = st.draining
            st = fresh
            self._states[rep.index] = st
            if self._gray is not None:
                # a fresh process must not inherit its predecessor's
                # latency record either
                self._gray.forget(rep.index)
        return st

    def _probe(self, rep):
        """GET one replica's /healthz then /statz (2 s timeouts);
        returns (healthy, statz)."""
        try:
            code, body = self._get_json(rep.base_url + "/healthz",
                                        timeout=2.0)
            if code != 200 or not body.get("ok"):
                return False, None
        except Exception:
            return False, None
        try:
            _, statz = self._get_json(rep.base_url + "/statz",
                                      timeout=2.0)
            return True, statz
        except Exception:
            return False, None

    def poll_once(self):
        """One health+load sweep over the pool (the poller thread calls
        this every ``route_poll_ms``; tests and the reload gate call it
        directly for determinism). Replicas are probed CONCURRENTLY —
        one hung /healthz (the failure ejection exists for) must not
        stretch the sweep and stale every sibling's score."""
        reps = self.pool.snapshot()
        probes = {}
        futures = {}
        for rep in reps:
            with self._lock:
                self._state_for(rep)
            if not rep.ready:
                # known-down (starting/restarting): not a health MISS —
                # eject bookkeeping is for processes that answer wrong,
                # not processes the pool already knows are absent
                continue
            futures[rep.index] = self._probe_pool().submit(self._probe,
                                                           rep)
        for index, fut in futures.items():
            probes[index] = fut.result()
        for rep in reps:
            if rep.index not in probes:
                continue
            healthy, statz = probes[rep.index]
            with self._lock:
                st = self._state_for(rep)
                if healthy:
                    st.failures = 0
                    st.statz = statz
                    st.statz_t = time.monotonic()
                    st.tier = str(statz.get("tier") or "")
                    st.score = self.statz_load(statz)
                    st.peak_load = max(st.peak_load,
                                       st.score + st.inflight)
                    readmitted = False
                    gray_released = False
                    if st.ejected:
                        held = st.gray_ejected
                        if held and st.gray_t is not None and \
                                time.monotonic() - st.gray_t \
                                >= self.gray_hold_s:
                            # the gray hold expired: forget the stale
                            # latency record (an ejected replica gets
                            # no traffic, so the signal cannot clear
                            # itself) and release the slot into the
                            # NORMAL probation cycle below
                            st.gray_ejected = False
                            st.gray_t = None
                            if self._gray is not None:
                                self._gray.forget(rep.index)
                            gray_released = True
                            held = False
                        if not held:
                            st.ok_streak += 1
                            if st.ok_streak >= self.readmit_after:
                                st.ejected = False
                                st.ok_streak = 0
                                readmitted = True
                else:
                    st.ok_streak = 0
                    st.failures += 1
                    if not st.ejected and st.failures >= self.eject_after:
                        st.ejected = True
                        ejected_now = True
                    else:
                        ejected_now = False
            if healthy:
                from .. import profiler as _prof
                _prof.update_router_counters(
                    router_peak_load=st.peak_load)
                if gray_released:
                    self._count("router_gray_readmits")
                    _prof.update_grayfail_counters(gray_readmits=1)
                if readmitted:
                    self._record("router_replica_readmit",
                                 replica=rep.index)
                    self._count("router_readmits")
            elif ejected_now:
                self._record("router_replica_eject",
                             replica=rep.index,
                             failures=self.eject_after)
                self._count("router_ejects")
        self._gray_poll(reps)
        self._update_pressure(reps)

    def _gray_poll(self, reps):
        """Feed per-replica proxied-latency EWMAs into the skew
        detector and eject a condemned replica (drain into the normal
        probation cycle) even though its /healthz answers 200. The
        JUDGEMENT is resilience.grayfail's; only the policy — drain +
        eject, never the last routable replica, durable events — lives
        here."""
        if self._gray is None:
            return
        to_record = []
        with self._lock:
            routable = 0
            observable = []
            for rep in reps:
                st = self._states.get(rep.index)
                if st is None or not rep.ready:
                    continue
                if st.ejected or st.draining:
                    continue
                routable += 1
                if st.lat_n > 0 and st.lat_ewma is not None:
                    observable.append((rep.index, st))
            for idx, st in observable:
                self._gray.observe(idx, st.lat_ewma)
            for idx, v in self._gray.evaluate().items():
                st = self._states.get(idx)
                if st is None or not v.changed:
                    continue
                if v.state == _SUSPECT:
                    to_record.append(("gray_suspected", idx, v, None))
                elif v.state == _CONDEMNED and not st.ejected:
                    if routable <= 1:
                        # a slow answer beats no answer: the last
                        # routable replica is never gray-ejected
                        continue
                    st.ejected = True
                    st.gray_ejected = True
                    st.gray_t = time.monotonic()
                    st.ok_streak = 0
                    routable -= 1
                    to_record.append(("gray_mitigated", idx, v,
                                      "eject"))
        from .. import profiler as _prof
        for kind, idx, v, action in to_record:
            info = {"replica": idx,
                    "metric": "proxied_latency_ewma_ms",
                    "stat": round(v.stat, 3),
                    "baseline": round(v.baseline, 3),
                    "threshold": round(v.threshold, 3),
                    "streak": v.streak}
            if action is not None:
                info["action"] = action
            self._record(kind, **info)
            if action is None:
                _prof.update_grayfail_counters(gray_suspected=1)
            else:
                self._count("router_gray_ejects")
                _prof.update_grayfail_counters(gray_ejects=1)

    def _update_pressure(self, reps):
        """Refresh the per-model autoscale signal from the healthy
        replicas' latest statz (formula: module docstring)."""
        backlog, capacity, requests, sheds = {}, {}, {}, {}
        with self._lock:
            for rep in reps:
                st = self._states.get(rep.index)
                if st is None or st.ejected or st.statz is None:
                    continue
                z = st.statz
                for name in (z.get("models") or {}):
                    gens = z.get("generation") or {}
                    if name in gens:
                        g = gens[name]
                        backlog[name] = backlog.get(name, 0.0) + \
                            g.get("queued", 0) + g.get("running", 0)
                        capacity[name] = capacity.get(name, 0.0) + \
                            max(g.get("max_running", 1), 1)
                        requests[name] = requests.get(name, 0.0) + \
                            g.get("submitted", 0)
                        sheds[name] = sheds.get(name, 0.0) + g.get("shed",
                                                                   0)
                    else:
                        # compiled model: the micro-batch queue is
                        # service-global; attribute it whole (an upper
                        # bound — honest for the scale-up decision)
                        backlog[name] = backlog.get(name, 0.0) + \
                            z.get("pending", 0)
                        capacity[name] = capacity.get(name, 0.0) + \
                            max(z.get("max_batch", 1), 1)
                        requests[name] = requests.get(name, 0.0) + \
                            z.get("requests", 0)
                        sheds[name] = sheds.get(name, 0.0) + z.get("shed",
                                                                   0)
            pressure = {}
            for name in backlog:
                prev_req, prev_shed = self._prev_model_counts.get(
                    name, (requests[name], sheds[name]))
                dreq = max(requests[name] - prev_req, 0.0)
                dshed = max(sheds[name] - prev_shed, 0.0)
                shed_rate = dshed / dreq if dreq > 0 else (
                    1.0 if dshed > 0 else 0.0)
                pressure[name] = round(
                    backlog[name] / max(capacity[name], 1.0) + shed_rate,
                    4)
                self._prev_model_counts[name] = (requests[name],
                                                 sheds[name])
            # EWMA smoothing: the autoscaler's signal. Seeded with the
            # first raw sample; a model that vanished from every statz
            # decays from its last value instead of sticking (an empty
            # poll sweep must read as pressure falling to zero)
            a = self.pressure_alpha
            ewma = {}
            for name in set(pressure) | set(self._pressure_ewma):
                raw = pressure.get(name, 0.0)
                prev = self._pressure_ewma.get(name)
                s = round(raw if prev is None
                          else a * raw + (1.0 - a) * prev, 4)
                if name not in pressure and s <= 1e-3:
                    continue   # fully decayed and gone from every statz
                ewma[name] = s
            self._pressure = pressure
            self._pressure_ewma = ewma

    def start_polling(self):
        """Start the background poll thread (idempotent)."""
        if self._poller is not None:
            return
        self._poller = threading.Thread(target=self._poll_loop,
                                        daemon=True,
                                        name="paddle_tpu-router-poll")
        self._poller.start()

    def _poll_loop(self):
        while not self._closed:
            try:
                self.poll_once()
            except Exception as e:   # the poller must outlive any glitch
                record_event("router_poll_error", site="serving.route",
                             error=repr(e))
            # the sleep rides an event: a membership change (grow,
            # shrink, restart respawn) wakes the poller immediately so
            # the new fleet shape is scored mid-flight, and close()
            # does not wait out a full interval
            self._poll_wake.wait(self.poll_s)
            self._poll_wake.clear()

    def notify_membership(self):
        """Pool membership changed (grow/shrink/restart/lost): wake the
        poller now instead of at its next timer tick. Registered with
        the pool's ``on_membership`` hook at construction."""
        self._poll_wake.set()

    def close(self):
        self._closed = True
        self._poll_wake.set()
        if self._poller is not None:
            self._poller.join(timeout=self.poll_s + 2.0)
        with self._lock:
            exec_, self._probe_exec = self._probe_exec, None
            hexec, self._hedge_exec = self._hedge_exec, None
        if exec_ is not None:
            exec_.shutdown(wait=False)
        if hexec is not None:
            hexec.shutdown(wait=False)

    # -- the autoscaler's handles -------------------------------------------
    def pressure_raw(self):
        """Latest per-model raw pressure (one poll window)."""
        with self._lock:
            return dict(self._pressure)

    def pressure_smoothed(self):
        """Latest per-model EWMA-smoothed pressure — the only signal
        the autoscaler acts on."""
        with self._lock:
            return dict(self._pressure_ewma)

    def set_draining(self, index, draining):
        """Hold new work off replica ``index`` (or release it) — the
        autoscaler's drain-first step before a shrink. Returns whether
        a state for the slot existed."""
        with self._lock:
            st = self._states.get(index)
            if st is None:
                for rep in self.pool.snapshot():
                    if rep.index == index:
                        st = self._state_for(rep)
                        break
            if st is None:
                return False
            st.draining = bool(draining)
            return True

    def replica_inflight(self, index):
        """Router-tracked proxied requests outstanding at ``index`` —
        what the drain step waits to hit zero."""
        with self._lock:
            st = self._states.get(index)
            return st.inflight if st is not None else 0

    def forget(self, index):
        """Drop the router-side state of a slot the pool retired —
        a future slot reusing the index must start clean."""
        with self._lock:
            self._states.pop(index, None)
            if self._gray is not None:
                self._gray.forget(index)

    # -- picking -------------------------------------------------------------
    def _routable(self, exclude=(), tier=None):
        """``tier`` filters to one serving class (None = any, including
        untiered). A replica holding a ``kv_pool_exhausted`` backoff is
        skipped — its own Retry-After said when capacity plausibly
        exists; re-dispatching sooner just re-feeds the exhausted pool
        — unless EVERY candidate is backing off (a slow answer beats a
        blanket 503 when the whole class is page-starved)."""
        out, held = [], []
        reps = self.pool.snapshot()
        now = time.monotonic()
        with self._lock:
            for rep in reps:
                if rep.index in exclude or not rep.ready:
                    continue
                st = self._state_for(rep)
                if st.ejected or st.draining:
                    continue
                if tier is not None and st.tier != tier:
                    continue
                (held if st.backoff_until > now else out).append((rep, st))
        return out or held

    def pick(self, exclude=(), tier=None):
        """The least-loaded healthy replica (or the next in rotation
        under round_robin); None when nothing is routable."""
        cands = self._routable(exclude, tier=tier)
        if not cands:
            return None
        if self.policy == "round_robin":
            with self._lock:
                cands.sort(key=lambda c: c[0].index)
                i = self._rr_next % len(cands)
                self._rr_next += 1
            return cands[i][0]
        with self._lock:
            # deterministic tiebreak: score, then fewer total routed,
            # then index
            best = min(cands, key=lambda c: (c[1].score + c[1].inflight,
                                             c[1].routed, c[0].index))
        return best[0]

    def tier_signal(self, tier):
        """The per-tier autoscale signal, class-correct by design:
        ``prefill`` load arrives as a queue (mean generative backlog +
        router-tracked inflight per routable prefill replica — prompt
        passes block the handler, so the router's own outstanding count
        IS the queue); ``decode`` capacity is page inventory (mean KV
        page-pool occupancy fraction per routable decode replica). 0.0
        when the tier has no routable member with a statz snapshot."""
        vals = []
        reps = self.pool.snapshot()
        with self._lock:
            for rep in reps:
                st = self._states.get(rep.index)
                if st is None or not rep.ready or st.ejected \
                        or st.draining or st.tier != tier:
                    continue
                z = st.statz
                if z is None:
                    continue
                gens = z.get("generation") or {}
                if tier == "prefill":
                    q = float(z.get("pending", 0)) + st.inflight
                    for g in gens.values():
                        q += float(g.get("queued", 0)) \
                            + float(g.get("running", 0))
                    vals.append(q)
                else:
                    frac = 0.0
                    for g in gens.values():
                        pu = g.get("page_utilization", 0.0)
                        if isinstance(pu, dict):
                            pu = pu.get("frac", 0.0)
                        frac = max(frac, float(pu))
                    vals.append(frac)
        return round(sum(vals) / len(vals), 4) if vals else 0.0

    def replica_tier(self, index):
        """The cached serving class of slot ``index`` (None = never
        polled healthy) — the tiered autoscaler's victim filter."""
        with self._lock:
            st = self._states.get(index)
            return st.tier if st is not None else None

    def _note_backpressure(self, index, payload):
        """A ``kv_pool_exhausted`` 429 holds its replica out of pick()
        for the replica's OWN Retry-After hint (capped at 10 s — the
        poller keeps refreshing real state underneath): honest
        backpressure, distinct from the eject machinery, which is for
        replicas answering WRONG."""
        if not isinstance(payload, dict) or \
                payload.get("kind") != "kv_pool_exhausted":
            return
        try:
            retry_s = float(payload.get("retry_after_ms") or 0.0) / 1e3
        except (TypeError, ValueError):
            retry_s = 0.0
        hold = min(max(retry_s, self.poll_s), 10.0)
        with self._lock:
            st = self._states.get(index)
            if st is not None:
                st.backoff_until = time.monotonic() + hold
        self._count("router_backpressure_holds")

    # -- proxying ------------------------------------------------------------
    def retry_after_ms(self):
        """Back-off hint for the router's own sheds (no healthy
        replica): recent proxied p50 if known, else one poll interval."""
        with self._lock:
            lat = list(self._latency_ms)
        base = _percentile(lat, 0.50) if lat else self.poll_s * 1e3
        return max(base, self.poll_s * 1e3, 50.0)

    @staticmethod
    def _fold_latency(st, lat_ms, alpha=0.3):
        """Fold one proxied answer into the replica's latency EWMA —
        the per-member metric the gray-failure detector judges.
        Caller holds the state lock."""
        st.lat_n += 1
        st.lat_ewma = lat_ms if st.lat_ewma is None else \
            alpha * lat_ms + (1.0 - alpha) * st.lat_ewma

    def _hedge_deadline_s(self):
        """The p99-derived hedge deadline in seconds, floored at
        route_hedge_min_ms (the floor alone until 20 samples exist —
        an empty histogram must not hedge everything)."""
        with self._lock:
            lat = list(self._latency_ms)
        p99 = _percentile(lat, 0.99) if len(lat) >= 20 else 0.0
        return max(p99, self.hedge_min_ms) / 1e3

    def _hedge_allowed(self):
        """Budget gate: hedges fired so far stay under
        hedge_budget x proxied requests — tail-chasing must never add
        unbounded load to an already-melting fleet."""
        with self._lock:
            req = self._counts.get("router_requests", 0)
            fired = self._counts.get("router_hedges", 0)
        return (fired + 1) <= self.hedge_budget * max(req, 1)

    def _spawn_post(self, rep, path, body, timeout):
        """One replica POST on the hedge executor with the full
        per-replica bookkeeping (inflight, routed, latency EWMA)
        attached to the future — the hedged path needs BOTH attempts
        tracked even though only one answer is consumed; the loser's
        done-callback still settles its replica's books."""
        with self._lock:
            st = self._state_for(rep)
            st.inflight += 1
            st.routed += 1
            st.peak_load = max(st.peak_load, st.score + st.inflight)
        t0 = time.monotonic()
        fut = self._hedge_pool().submit(
            self._post_json, rep.base_url + path, body, timeout)

        def _settle(_f):
            with self._lock:
                st.inflight -= 1
                lat = (time.monotonic() - t0) * 1e3
                self._latency_ms.append(lat)
                del self._latency_ms[:-4096]
                self._fold_latency(st, lat)
        fut.add_done_callback(_settle)
        return fut

    def _post_hedged(self, rep, path, body, timeout):
        """Attempt 0 of an idempotent ``:predict`` with hedging armed:
        fire the primary, wait out the hedge deadline, then fire at
        most ONE hedged attempt at the next-best replica (budget
        permitting); the FIRST ANSWER wins and the loser is discarded
        on arrival. Returns (status, payload, winner_index,
        hedge_indices); status None = every fired attempt died on
        transport (payload carries the last error's repr) — the
        caller's normal failover takes over."""
        from concurrent.futures import wait, FIRST_COMPLETED
        from .. import profiler as _prof
        fault_point("serving.route")
        futs = {self._spawn_post(rep, path, body, timeout): rep.index}
        extra = []
        done, _ = wait(list(futs),
                       timeout=min(self._hedge_deadline_s(), timeout),
                       return_when=FIRST_COMPLETED)
        if not done:
            hedge = self.pick(exclude=(rep.index,))
            if hedge is not None and self._hedge_allowed():
                self._count("router_hedges")
                _prof.update_grayfail_counters(router_hedges=1)
                futs[self._spawn_post(hedge, path, body,
                                      timeout)] = hedge.index
                extra.append(hedge.index)
        last_err = None
        remaining = set(futs)
        while remaining:
            done, _ = wait(list(remaining),
                           return_when=FIRST_COMPLETED)
            for f in done:
                remaining.discard(f)
                if f.exception() is not None:
                    last_err = f.exception()
                    continue
                status, payload, _hdrs = f.result()
                widx = futs[f]
                if widx != rep.index:
                    self._count("router_hedge_wins")
                    _prof.update_grayfail_counters(router_hedge_wins=1)
                return status, payload, widx, extra
        return None, repr(last_err), None, extra

    def proxy(self, path, body, deadline_ms=None, tier=None):
        """Route one POST to the best replica with one failover retry.
        Returns (status, body_dict, replica_index_or_None). Transport
        failures and 429/503 answers try the next-best once (the first
        replica excluded); the second answer is final. ``deadline_ms``
        is ONE budget shared across both attempts — a slow first
        replica eats into the retry's window, the client never waits
        2x its deadline. A ``route_failover`` event is recorded only
        once the retry has an actual target: a lone replica's 429 must
        not read as a failover in /statz. No routable replica ->
        (503, shed body, None). With ``route_hedge_budget`` > 0 an
        idempotent ``:predict``'s FIRST attempt may fire one hedged
        sibling attempt past the p99 deadline (see ``_post_hedged``);
        ``:generate`` never hedges."""
        deadline_t = None
        if deadline_ms is not None:
            deadline_t = time.monotonic() + max(float(deadline_ms) / 1e3,
                                                0.05)
        tried = []
        last_answer = None
        pending_failover = None    # failed attempt awaiting a retry target
        self._count("router_requests")
        for attempt in range(2):
            rep = self.pick(exclude=tried, tier=tier)
            if rep is None:
                break
            if pending_failover is not None:
                self._record("route_failover",
                             path=path, **pending_failover)
                self._count("router_failovers")
                pending_failover = None
            tried.append(rep.index)
            timeout = self.proxy_timeout_s
            if deadline_t is not None:
                timeout = min(timeout,
                              max(deadline_t - time.monotonic(), 0.05))
            if attempt == 0 and self.hedge_budget > 0 \
                    and path.endswith(":predict"):
                status, payload, widx, extra = self._post_hedged(
                    rep, path, body, timeout)
                for i in extra:
                    if i not in tried:
                        tried.append(i)
                if status is None:
                    pending_failover = {"replica": rep.index,
                                        "attempt": attempt + 1,
                                        "error": payload}
                    continue
                if status in (429, 503):
                    if status == 429 and widx is not None:
                        self._note_backpressure(widx, payload)
                    last_answer = (status, payload, widx)
                    pending_failover = {"replica": widx,
                                        "attempt": attempt + 1,
                                        "status": status}
                    continue
                return status, payload, widx
            with self._lock:
                st = self._state_for(rep)
                st.inflight += 1
                st.routed += 1
                st.peak_load = max(st.peak_load, st.score + st.inflight)
            t0 = time.monotonic()
            try:
                fault_point("serving.route")
                status, payload, _ = self._post_json(
                    rep.base_url + path, body, timeout)
            except Exception as e:
                pending_failover = {"replica": rep.index,
                                    "attempt": attempt + 1,
                                    "error": repr(e)}
                continue
            finally:
                with self._lock:
                    st.inflight -= 1
                    lat = (time.monotonic() - t0) * 1e3
                    self._latency_ms.append(lat)
                    del self._latency_ms[:-4096]
                    self._fold_latency(st, lat)
            if status == 429:
                self._note_backpressure(rep.index, payload)
            if status in (429, 503) and attempt == 0:
                # exhaustion is an honest answer, but a sibling may
                # have room: one retry at the next-best replica
                last_answer = (status, payload, rep.index)
                pending_failover = {"replica": rep.index,
                                    "attempt": attempt + 1,
                                    "status": status}
                continue
            return status, payload, rep.index
        if last_answer is not None:
            return last_answer
        if tried:
            # replicas WERE routable — both attempts died on transport
            # (e.g. the whole fleet crashed between polls). Distinct
            # from an empty fleet: 503 either way (the client should
            # retry after the restart window), but counted and labelled
            # honestly so /statz doesn't misread a transient double
            # failure as an ejected fleet.
            self._count("router_proxy_failed")
            self._record("request_shed",
                         reason="failover_exhausted", path=path)
            return 503, {"error": "all failover attempts failed "
                                  "(tried %s)" % tried,
                         "kind": "failover_exhausted"}, None
        self._count("router_no_replica")
        self._record("request_shed",
                     reason="no_replica", path=path)
        return 503, {"error": "no healthy replica available",
                     "kind": "no_replica"}, None

    def _post_tracked(self, rep, path, body, timeout):
        """One POST with the full per-replica bookkeeping (inflight,
        routed, latency EWMA) — the two-hop disagg path's transport.
        Returns (status, payload); transport failures propagate."""
        with self._lock:
            st = self._state_for(rep)
            st.inflight += 1
            st.routed += 1
            st.peak_load = max(st.peak_load, st.score + st.inflight)
        t0 = time.monotonic()
        try:
            status, payload, _ = self._post_json(rep.base_url + path,
                                                 body, timeout)
            return status, payload
        finally:
            with self._lock:
                st.inflight -= 1
                lat = (time.monotonic() - t0) * 1e3
                self._latency_ms.append(lat)
                del self._latency_ms[:-4096]
                self._fold_latency(st, lat)

    def proxy_generate(self, name, body, deadline_ms=None):
        """Route one ``:generate``. On a fleet whose routable set holds
        BOTH serving classes this is the disaggregated two-hop —
        ``:prefill`` at the least-loaded prefill replica, the returned
        artifact shipped via ``:decode`` to the least-loaded decode
        replica (fault site ``serving.ship``); anything less tiered
        falls through to the plain single-hop :meth:`proxy`. Failure
        semantics mirror :func:`~paddle_tpu.serving.disagg.ship`: a
        prefill-tier miss or a decode replica dying mid-handoff
        re-routes the ORIGINAL request to the decode tier, which
        re-prefills locally — slower, bit-identical, never lost
        (recorded ``handoff_failed``). Returns (status, body_dict,
        replica_index_or_None) like :meth:`proxy`."""
        path = "/v1/models/%s:generate" % name
        pre = self.pick(tier="prefill")
        if pre is None or not self._routable(tier="decode"):
            return self.proxy(path, body, deadline_ms=deadline_ms)
        deadline_t = None
        if deadline_ms is not None:
            deadline_t = time.monotonic() + max(float(deadline_ms) / 1e3,
                                                0.05)

        def budget():
            t = self.proxy_timeout_s
            if deadline_t is not None:
                t = min(t, max(deadline_t - time.monotonic(), 0.05))
            return t

        self._count("router_requests")
        # hop 1: the prompt pass on the prefill tier
        try:
            fault_point("serving.route")
            status, payload = self._post_tracked(
                pre, "/v1/models/%s:prefill" % name, body, budget())
        except Exception as e:
            status, payload = None, {"error": repr(e)}
        artifact = (payload or {}).get("artifact") \
            if status == 200 else None
        if artifact is None:
            if status == 429:
                self._note_backpressure(pre.index, payload)
            # the prefill tier missing its hop must not fail the
            # request: the decode tier runs it whole, single-hop
            self._count("router_handoff_fallbacks")
            return self.proxy(path, body, deadline_ms=deadline_ms,
                              tier="decode")
        # hop 2: ship the artifact to the decode tier (one failover)
        tried = [pre.index]
        last_answer = None
        for attempt in range(2):
            dec = self.pick(exclude=tried, tier="decode")
            if dec is None:
                break
            tried.append(dec.index)
            try:
                fault_point("serving.ship")
                status, payload = self._post_tracked(
                    dec, "/v1/models/%s:decode" % name,
                    {"artifact": artifact, "deadline_ms": deadline_ms},
                    budget())
            except Exception as e:
                # the decode replica died mid-handoff: the artifact is
                # gone with the connection — record it and RE-PREFILL
                # by routing the original request to the decode tier
                record_durable_event(
                    "handoff_failed", site="serving.ship",
                    state_dir=self.state_dir, model=name,
                    prefill_replica=pre.index, decode_replica=dec.index,
                    error=repr(e))
                self._count("router_handoff_failed")
                from .. import profiler as _prof
                _prof.update_generation_counters(gen_handoff_failed=1)
                return self.proxy(path, body, deadline_ms=deadline_ms,
                                  tier="decode")
            if status == 429:
                self._note_backpressure(dec.index, payload)
            if status in (429, 503) and attempt == 0:
                last_answer = (status, payload, dec.index)
                continue
            if status == 200:
                self._count("router_handoffs")
            return status, payload, dec.index
        if last_answer is not None:
            return last_answer
        self._count("router_no_replica")
        self._record("request_shed", reason="no_replica", path=path)
        return 503, {"error": "no routable decode replica for the "
                              "handoff", "kind": "no_replica"}, None

    def models(self):
        """GET /v1/models proxied from the best replica (the fleet is
        homogeneous by construction)."""
        rep = self.pick()
        if rep is None:
            return 503, {"error": "no healthy replica available",
                         "kind": "no_replica"}
        try:
            return self._get_json(rep.base_url + "/v1/models",
                                  timeout=5.0)
        except Exception as e:
            return 502, {"error": repr(e), "kind": "route"}

    # -- rolling reload ------------------------------------------------------
    _READY_GATE_S = 60.0

    def _await_ready(self, rep, name, timeout=None):
        """Health-gate one reloaded replica: /healthz ok AND the model
        present and not draining in the readiness detail."""
        deadline = time.monotonic() + (timeout or self._READY_GATE_S)
        while time.monotonic() < deadline:
            try:
                code, body = self._get_json(rep.base_url + "/healthz",
                                            timeout=2.0)
                ready = (body.get("ready") or {}).get(name)
                if code == 200 and body.get("ok") and ready is not None \
                        and not ready.get("draining"):
                    return True
            except Exception:
                pass
            time.sleep(min(self.poll_s, 0.2))
        return False

    def _current_dirname(self, rep, name):
        """What artifact is ``name`` serving on ``rep`` right now (the
        rollback target for a partial rollout)."""
        try:
            _, info = self._get_json(rep.base_url + "/v1/models",
                                     timeout=5.0)
            return (info.get(name) or {}).get("dirname")
        except Exception:
            return None

    def rolling_reload(self, name, dirname):
        """Fan ``:reload {dirname}`` over the fleet ONE replica at a
        time, each drained first and health-gated after. On the first
        failure: abort, roll already-reloaded replicas back to the
        artifact they were serving, record ``reload_rollback``, and
        leave the fleet intact. Ejected (health-failing) replicas are
        SKIPPED, not visited — one wedged replica must not block the
        healthy majority's upgrade by hanging its reload and aborting
        the rollout; skipped indices ride the answer so the operator
        knows to re-issue ``:reload`` once they recover (a skipped
        replica readmits on its OLD artifact). Already-draining
        replicas (an autoscaler shrink in progress) are skipped the
        same way. The whole rollout holds the pool's ONE
        ``membership_lock``, so a shrink cannot land mid-reload and
        have the loop probe a replica the autoscaler just drained.
        Returns (status, body)."""
        with self._membership_lock:
            reps, skipped = [], []
            for r in self.pool.snapshot():
                with self._lock:
                    st = self._state_for(r)
                    ineligible = st.ejected or st.draining
                if r.ready and not ineligible:
                    reps.append(r)
                else:
                    skipped.append(r.index)
            if not reps:
                return 503, {"error": "no healthy replica to reload",
                             "kind": "no_replica",
                             "skipped_replicas": skipped}
            done = []        # [(rep, previous_dirname)]
            for rep in reps:
                prev = self._current_dirname(rep, name)
                # index-based, not via a captured state object: if the
                # replica crashes and respawns mid-reload, the clear
                # below must land on the CURRENT slot state, not a
                # stale generation's
                self.set_draining(rep.index, True)
                try:
                    try:
                        status, payload, _ = self._post_json(
                            rep.base_url + "/v1/models/%s:reload" % name,
                            {"dirname": dirname}, self.proxy_timeout_s)
                    except Exception as e:
                        status, payload = 502, {"error": repr(e),
                                                "kind": "route"}
                    gated = status == 200 and self._await_ready(rep, name)
                    if status == 200 and not gated:
                        status, payload = 502, {
                            "error": "replica %d reloaded but failed the "
                                     "health gate" % rep.index,
                            "kind": "health_gate"}
                finally:
                    self.set_draining(rep.index, False)
                if status != 200:
                    rolled_back, rb_failed = self._roll_back(name, done)
                    self._record(
                        "reload_rollback",
                        model=name, dirname=dirname,
                        failed_replica=rep.index,
                        reloaded_then_rolled_back=rolled_back,
                        rollback_failed=rb_failed,
                        error=payload.get("error"))
                    self._count("router_reload_rollbacks")
                    payload = dict(payload)
                    payload.update({
                        "failed_replica": rep.index,
                        "rolled_back_replicas": rolled_back,
                        "rollback_failed_replicas": rb_failed,
                        "skipped_replicas": skipped,
                        "fleet_intact": not rb_failed})
                    return status, payload
                done.append((rep, prev))
            self._count("router_reloads")
            self._record("router_reload", model=name,
                         dirname=dirname,
                         replicas=[r.index for r, _ in done],
                         skipped=skipped)
            return 200, {"model": name, "dirname": dirname,
                         "replicas": [r.index for r, _ in done],
                         "skipped_replicas": skipped}

    def _roll_back(self, name, done):
        """Re-reload the already-upgraded replicas onto their previous
        artifact (one at a time, same drain+gate). Returns (rolled,
        failed): ``failed`` holds replicas left on the NEW artifact —
        their previous dirname was unknown or the rollback reload
        itself failed — so the abort answer can report a version-split
        fleet honestly instead of claiming it intact."""
        rolled, failed = [], []
        for rep, prev in done:
            if not prev:
                failed.append(rep.index)
                continue
            self.set_draining(rep.index, True)
            try:
                try:
                    status, _, _ = self._post_json(
                        rep.base_url + "/v1/models/%s:reload" % name,
                        {"dirname": prev}, self.proxy_timeout_s)
                except Exception:
                    status = 502
                if status == 200 and self._await_ready(rep, name):
                    rolled.append(rep.index)
                else:
                    # a 200 whose health gate never passed is NOT a
                    # rollback — the replica is wedged, not restored
                    failed.append(rep.index)
            finally:
                self.set_draining(rep.index, False)
        return rolled, failed

    # -- stats ---------------------------------------------------------------
    def stats(self):
        """RouterStats snapshot (the router's own /statz)."""
        reps = {r.index: r for r in self.pool.snapshot()}
        with self._lock:
            lat = list(self._latency_ms)
            replicas = {}
            for idx, st in sorted(self._states.items()):
                rep = reps.get(idx)
                replicas[str(idx)] = {
                    "url": rep.base_url if rep is not None else None,
                    "ready": bool(rep is not None and rep.ready),
                    "generation": st.generation,
                    "tier": st.tier,
                    "backpressure_hold_s": round(
                        max(st.backoff_until - time.monotonic(), 0.0), 3),
                    "ejected": st.ejected,
                    "gray_ejected": st.gray_ejected,
                    "latency_ewma_ms": (round(st.lat_ewma, 3)
                                        if st.lat_ewma is not None
                                        else None),
                    "draining": st.draining,
                    "health_failures": st.failures,
                    "routed": st.routed,
                    "inflight": st.inflight,
                    "score": round(st.score + st.inflight, 4),
                    "peak_load": round(st.peak_load, 4),
                    "statz_age_s": (
                        round(time.monotonic() - st.statz_t, 3)
                        if st.statz_t is not None else None),
                }
            counts = dict(self._counts)
            pressure = dict(self._pressure)
            pressure_smoothed = dict(self._pressure_ewma)
        routed = [r["routed"] for r in replicas.values()] or [0]
        # one fleet-wide autoscaler, or a LIST of per-tier ones
        autoscale = None
        if self.autoscaler is not None:
            if isinstance(self.autoscaler, (list, tuple)):
                autoscale = [a.stats() for a in self.autoscaler]
            else:
                autoscale = self.autoscaler.stats()
        out = {
            "policy": self.policy,
            "replicas": replicas,
            "pressure": pressure,
            "pressure_smoothed": pressure_smoothed,
            "proxied": counts.get("router_requests", 0),
            "failovers": counts.get("router_failovers", 0),
            "no_replica": counts.get("router_no_replica", 0),
            "proxy_failed": counts.get("router_proxy_failed", 0),
            "ejects": counts.get("router_ejects", 0),
            "readmits": counts.get("router_readmits", 0),
            "gray_ejects": counts.get("router_gray_ejects", 0),
            "gray_readmits": counts.get("router_gray_readmits", 0),
            "hedges": counts.get("router_hedges", 0),
            "hedge_wins": counts.get("router_hedge_wins", 0),
            "handoffs": counts.get("router_handoffs", 0),
            "handoff_failed": counts.get("router_handoff_failed", 0),
            "handoff_fallbacks": counts.get("router_handoff_fallbacks", 0),
            "backpressure_holds": counts.get("router_backpressure_holds",
                                             0),
            "hedge_budget": self.hedge_budget,
            "gray_ratio": self.gray_ratio,
            "reloads": counts.get("router_reloads", 0),
            "reload_rollbacks": counts.get("router_reload_rollbacks", 0),
            "latency_ms_p50": _percentile(lat, 0.50),
            "latency_ms_p99": _percentile(lat, 0.99),
            "routed_max": max(routed),
            "routed_min": min(routed),
            "pool": self.pool.describe(),
        }
        if autoscale is not None:
            out["autoscale"] = autoscale
        return out

    def reset_stats(self):
        """Zero the routing/latency counters and per-replica peaks (the
        bench's phase boundary); health state is preserved."""
        with self._lock:
            self._counts.clear()
            del self._latency_ms[:]
            for st in self._states.values():
                st.routed = 0
                st.peak_load = st.score + st.inflight


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "paddle_tpu-route"

    def log_message(self, fmt, *args):
        pass

    @property
    def router(self):
        return self.server.router

    def _reply(self, code, payload, retry_after_ms=None):
        write_json_reply(self, code, payload,
                         retry_after_ms=retry_after_ms)

    def do_GET(self):
        if self.path == "/healthz":
            st = self.router.stats()
            routable = [i for i, r in st["replicas"].items()
                        if r["ready"] and not r["ejected"]]
            self._reply(200, {"ok": True, "role": "router",
                              "routable_replicas": routable,
                              "policy": st["policy"]})
        elif self.path == "/statz":
            self._reply(200, self.router.stats())
        elif self.path == "/v1/models":
            code, body = self.router.models()
            self._reply(code, body)
        else:
            self._reply(404, {"error": "no route %r" % self.path,
                              "kind": "not_found"})

    def do_POST(self):
        try:
            body = read_json_body(self)
        except Exception as e:
            self.close_connection = True
            return self._reply(400, {"error": "bad JSON body: %s" % e,
                                     "kind": "bad_request"})
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
                if not (deadline_ms > 0):   # also rejects NaN
                    raise ValueError
            except (TypeError, ValueError):
                # the replica answers this 400 itself; a malformed
                # deadline must not detonate inside proxy() and drop
                # the connection without a reply
                return self._reply(
                    400, {"error": "deadline_ms must be a positive "
                                   "number, got %r"
                                   % body.get("deadline_ms"),
                          "kind": "bad_request"})
        for verb in (":predict", ":generate"):
            if self.path.startswith("/v1/models/") and \
                    self.path.endswith(verb):
                if verb == ":generate":
                    name = self.path[len("/v1/models/"):-len(verb)]
                    status, payload, replica = self.router.proxy_generate(
                        name, body, deadline_ms=deadline_ms)
                else:
                    status, payload, replica = self.router.proxy(
                        self.path, body, deadline_ms=deadline_ms)
                if replica is not None and isinstance(payload, dict):
                    payload = dict(payload)
                    payload["replica"] = replica
                retry = None
                if status in (429, 503):
                    retry = (payload or {}).get("retry_after_ms") \
                        or self.router.retry_after_ms()
                return self._reply(status, payload, retry_after_ms=retry)
        if self.path.startswith("/v1/models/") and \
                self.path.endswith(":reload"):
            name = self.path[len("/v1/models/"):-len(":reload")]
            dirname = body.get("dirname")
            if not dirname:
                return self._reply(400, {"error": 'reload wants '
                                                  '{"dirname": path}',
                                         "kind": "bad_request"})
            status, payload = self.router.rolling_reload(name, dirname)
            return self._reply(status, payload)
        self._reply(404, {"error": "no route %r" % self.path,
                          "kind": "not_found"})


def make_router_server(router, host="127.0.0.1", port=0):
    """Bind the front :class:`ThreadingHTTPServer` over ``router``
    (``port=0`` picks a free one). The caller owns ``serve_forever()``
    / ``shutdown()`` — reuse ``httpd.serve_until_shutdown`` for the
    signal-driven CLI loop."""
    server = ThreadingHTTPServer((host, port), _RouterHandler)
    server.daemon_threads = True
    server.router = router
    return server
