"""Disaggregated serving: prefill-tier engines + the KV handoff hop.

Prefill and decode are different machines wearing one API: prefill is
compute-bound and bursty (one big matmul wave per request, then done),
decode is memory-bound and steady (one small step per token, pinned to
the KV pool). A fleet of do-everything replicas sizes both phases with
one knob and scales them with one signal, so it is always wrong for at
least one of them. This module splits the roles — the reference
framework's signature move (the DistributeTranspiler rewriting one
program into cooperating trainer/pserver processes), applied to the
serving tier:

- :class:`PrefillEngine` is the prefill-class replica's engine: it runs
  ONLY the prompt pass (same compiled prefill faces and the same
  position-keyed device sampling as
  :class:`~paddle_tpu.serving.generator.GenerationEngine`, so the first
  token is bit-identical to a local prefill), then EXPORTS the finished
  KV pages and the request state as a :class:`HandoffArtifact` and
  frees its own pages — a prefill replica holds a request's memory for
  milliseconds, not for the decode lifetime.
- :class:`HandoffArtifact` is the wire unit: prompt, first sampled
  token (+ logprob), sampling params, pool geometry, and the raw K/V
  page contents. ``to_payload``/``from_payload`` give it a JSON body
  (base64 float arrays) for the HTTP hop between real replicas.
- :func:`ship` is the hop itself, fault site ``serving.ship``: deliver
  the artifact into a decode-class engine's
  ``submit_prefilled``. A failed hop NEVER loses the request — it is
  re-submitted as a plain prompt to the decode engine (which
  re-prefills locally: slower, identical output, recorded
  ``handoff_failed`` event). Overload/pool-exhaustion answers from the
  decode engine are honest backpressure and propagate unchanged; the
  fallback exists for the hop dying, not for the fleet being full.

Honest CPU-vs-TPU caveat (doc/serving.md spells it out): on this CPU
build the "ship" is a host round trip through numpy/base64 and the
decode side re-uploads the pages; a TPU deployment would DMA pages
between device HBMs (ICI/DCN) and the artifact would carry device
buffer handles, not bytes. The protocol, accounting, and failure
semantics are what this module pins down; the transport is the part a
TPU backend swaps.
"""
from __future__ import annotations

import base64
import time

import numpy as np

from ..resilience import fault_point, record_event
from .admission import ServingError
from .batcher import bucket_for, padding_buckets
from .kvcache import PagePool, pages_for

__all__ = ["HandoffArtifact", "PrefillEngine", "ship"]


class HandoffArtifact(object):
    """One finished prefill, packaged for the decode tier: the request
    state that makes the continuation bit-exact (prompt, first sampled
    token + logprob, temperature, seed, budget), the pool geometry the
    pages were written under, and the raw page contents
    (``k_pages``/``v_pages``, shape ``[L, n_pages, T, nh, dh]``)."""

    __slots__ = ("prompt", "first_token", "first_logprob", "temperature",
                 "seed", "max_new_tokens", "page_tokens", "num_layers",
                 "num_heads", "head_dim", "k_pages", "v_pages")

    def __init__(self, prompt, first_token, first_logprob, temperature,
                 seed, max_new_tokens, page_tokens, num_layers, num_heads,
                 head_dim, k_pages, v_pages):
        self.prompt = [int(t) for t in prompt]
        self.first_token = int(first_token)
        self.first_logprob = (None if first_logprob is None
                              else float(first_logprob))
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.max_new_tokens = int(max_new_tokens)
        self.page_tokens = int(page_tokens)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.k_pages = np.asarray(k_pages)
        self.v_pages = np.asarray(v_pages)

    @property
    def pages(self):
        return int(self.k_pages.shape[1])

    @property
    def kv_bytes(self):
        """Wire weight of the hop (both page arrays) — what the comm
        model would price as one inter-replica transfer."""
        return int(self.k_pages.nbytes + self.v_pages.nbytes)

    # -- wire format ----------------------------------------------------------
    def to_payload(self):
        """JSON-able dict (the ``:decode`` HTTP body): scalars inline,
        page contents as base64 of the raw little-endian bytes plus
        dtype/shape so the receive side rebuilds them exactly."""
        def pack(a):
            a = np.ascontiguousarray(a)
            return {"dtype": str(a.dtype), "shape": list(a.shape),
                    "data": base64.b64encode(a.tobytes()).decode("ascii")}
        return {"prompt": list(self.prompt),
                "first_token": self.first_token,
                "first_logprob": self.first_logprob,
                "temperature": self.temperature,
                "seed": self.seed,
                "max_new_tokens": self.max_new_tokens,
                "page_tokens": self.page_tokens,
                "num_layers": self.num_layers,
                "num_heads": self.num_heads,
                "head_dim": self.head_dim,
                "k_pages": pack(self.k_pages),
                "v_pages": pack(self.v_pages)}

    @classmethod
    def from_payload(cls, payload):
        """Inverse of :meth:`to_payload`; raises ValueError on a
        malformed body (the HTTP side maps it to 400)."""
        def unpack(obj):
            if not isinstance(obj, dict):
                raise ValueError("page block must be {dtype, shape, data}")
            raw = base64.b64decode(obj["data"])
            a = np.frombuffer(raw, dtype=np.dtype(obj["dtype"]))
            return a.reshape([int(d) for d in obj["shape"]]).copy()
        try:
            return cls(payload["prompt"], payload["first_token"],
                       payload.get("first_logprob"),
                       payload.get("temperature", 0.0),
                       payload.get("seed", 0),
                       payload.get("max_new_tokens", 16),
                       payload["page_tokens"], payload["num_layers"],
                       payload["num_heads"], payload["head_dim"],
                       unpack(payload["k_pages"]),
                       unpack(payload["v_pages"]))
        except (KeyError, TypeError) as e:
            raise ValueError("malformed handoff payload: %r" % (e,))


class PrefillEngine(object):
    """The prefill-class replica's engine: prompt pass + first-token
    sample + page export, nothing else — no decode loop, no continuous
    batching, no long-lived page residency. Synchronous by design: a
    prefill is one compiled call, and the HTTP server's thread-per-
    connection model already provides the concurrency.

    Shares the :class:`GenerationEngine` compile discipline: the fused
    prefill face (prefill + seeded device sampling in one jit) compiles
    once per prompt-length bucket; geometry (``page_tokens``, KV spec)
    must match the decode tier's pools or ``submit_prefilled`` rejects
    the artifact. ``kv_pages`` only needs to cover the LARGEST single
    prompt (pages are freed as soon as the artifact is exported), not a
    running set — the memory asymmetry that makes the tier split pay.
    """

    def __init__(self, model, kv_pages=None, page_tokens=None,
                 name="model", eos_id=None, device_sample=None):
        import jax
        from ..flags import FLAGS
        self.model = model
        self.name = name
        cfg = model.config
        self.eos_id = cfg.eos_id if eos_id is None else int(eos_id)
        self.max_context = int(cfg.max_seq)
        page_tokens = int(page_tokens if page_tokens is not None
                          else FLAGS.serve_page_tokens)
        if kv_pages is None:
            # enough for one max-length prompt: the working set is one
            # request deep (pages free at export), so the flag default
            # for a decode pool would be pure waste here
            kv_pages = pages_for(self.max_context, page_tokens)
        L, nh, dh = model.kv_spec
        self.pool = PagePool(int(kv_pages), page_tokens, L, nh, dh)
        self._kp, self._vp = self.pool.zeros()
        self.max_blocks = pages_for(self.max_context, page_tokens)
        self._buckets = padding_buckets(self.max_context)
        self._prefill = jax.jit(model.prefill_fn(), donate_argnums=(1, 2))
        if device_sample is None:
            device_sample = bool(FLAGS.serve_device_sample)
        self.device_sample = False
        self._prefill_s = None
        if device_sample:
            # same fused face as the decode tier's engine — the first
            # token must come from the SAME position-keyed RNG stream
            # the decode replica would have used locally, or the hop
            # would fork tempered outputs
            self._prefill_s = jax.jit(model.prefill_sample_fn(),
                                      donate_argnums=(1, 2))
            self.device_sample = True
        self._counts = {"prefills": 0, "prompt_tokens": 0,
                        "exported_pages": 0, "exported_bytes": 0}
        self._busy_s = 0.0
        self._closed = False

    def prefill(self, prompt, max_new_tokens=16, temperature=0.0, seed=0):
        """Run one prompt pass and export it: returns a
        :class:`HandoffArtifact` ready to :func:`ship`. Pages are
        allocated for the prompt only, gathered to host right after the
        compiled call, and freed before returning — this engine's pool
        occupancy is transient by construction. Raises
        :class:`PoolExhausted` (admission backpressure) or ValueError
        on an infeasible prompt, exactly like ``submit``."""
        import jax.numpy as jnp
        if self._closed:
            raise ServingError("prefill engine is closed")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be a non-empty token list")
        if any(t < 0 or t >= self.model.config.vocab_size for t in prompt):
            raise ValueError("prompt token out of range [0, %d)"
                             % self.model.config.vocab_size)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + int(max_new_tokens) > self.max_context:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the model "
                "context window (%d)" % (len(prompt), max_new_tokens,
                                         self.max_context))
        T = self.pool.page_tokens
        pages = self.pool.alloc(pages_for(len(prompt), T))
        row = np.full((self.max_blocks,), self.pool.trash_page, np.int32)
        row[:len(pages)] = pages
        t0 = time.monotonic()
        try:
            S_b = bucket_for(len(prompt), self._buckets)
            padded = np.zeros((S_b,), np.int32)
            padded[:len(prompt)] = prompt
            if self.device_sample:
                tok_d, logp_d, self._kp, self._vp = self._prefill_s(
                    self.model.params, self._kp, self._vp,
                    jnp.asarray(padded), np.int32(len(prompt)),
                    jnp.asarray(row), np.float32(temperature),
                    np.int32(int(seed) & 0x7FFFFFFF))
                tok, logp = int(tok_d), float(logp_d)
            else:
                last, self._kp, self._vp = self._prefill(
                    self.model.params, self._kp, self._vp,
                    jnp.asarray(padded), np.int32(len(prompt)),
                    jnp.asarray(row))
                from .generator import sample_token
                rng = np.random.RandomState(int(seed) & 0x7FFFFFFF)
                tok = sample_token(np.asarray(last), temperature, rng)
                logp = None
            # gather JUST the written pages to host — the export copy a
            # TPU backend would replace with a device-to-device DMA
            ids = jnp.asarray(np.asarray(pages, np.int32))
            k = np.asarray(self._kp[:, ids])
            v = np.asarray(self._vp[:, ids])
        finally:
            self._busy_s += time.monotonic() - t0
            self.pool.free(pages)
        self._counts["prefills"] += 1
        self._counts["prompt_tokens"] += len(prompt)
        self._counts["exported_pages"] += len(pages)
        art = HandoffArtifact(
            prompt, tok, logp, temperature, seed, max_new_tokens,
            T, self.pool.num_layers, self.pool.num_heads,
            self.pool.head_dim, k, v)
        self._counts["exported_bytes"] += art.kv_bytes
        return art

    @property
    def stats(self):
        return dict(self._counts, busy_s=round(self._busy_s, 4),
                    kv_pages=self.pool.num_pages,
                    page_tokens=self.pool.page_tokens)

    def close(self):
        self._closed = True


def ship(artifact, decode_engine, deadline_ms=None):
    """Deliver one handoff into a decode-class engine — the inter-tier
    hop, fault site ``serving.ship``. Returns the decode engine's
    request handle (``.wait()`` for the GenResult).

    Failure semantics (the tier split's whole safety story):

    - A hop failure — the armed fault, a geometry mismatch from a
      version-split fleet, the install face dying — re-submits the
      ORIGINAL prompt to the decode engine, which re-prefills locally:
      slower (the prefill ran twice), bit-identical (same seed, same
      position-keyed stream), never lost. Recorded ``handoff_failed``.
    - Overload/pool-exhaustion raised by the decode engine's admission
      are honest backpressure, NOT hop failures: they propagate to the
      caller (whose retry/backoff machinery owns them) — re-prefilling
      into a full pool would just burn a second prefill to hit the
      same wall.
    """
    from .admission import OverloadError
    from .kvcache import PoolExhausted
    try:
        fault_point("serving.ship")
        return decode_engine.submit_prefilled(artifact,
                                              deadline_ms=deadline_ms)
    except (OverloadError, PoolExhausted):
        raise
    except BaseException as e:
        record_event("handoff_failed", site="serving.ship",
                     model=getattr(decode_engine, "name", "?"),
                     pages=artifact.pages, error=repr(e))
        from .. import profiler as _prof
        _prof.update_generation_counters(gen_handoff_failed=1)
        return decode_engine.submit(
            artifact.prompt, max_new_tokens=artifact.max_new_tokens,
            temperature=artifact.temperature, seed=artifact.seed,
            deadline_ms=deadline_ms, spec_k=0)
