"""Online inference serving over AOT-compiled artifacts.

The reference deploys by multi-threading gradient machines behind its C
API (paddle/capi/gradient_machine.h:36
``paddle_gradient_machine_create_for_inference``) and the fluid inference
engine's ``Load`` — one request, one forward, per thread. The TPU-native
redesign inverts that: XLA wants FEW, LARGE dispatches, so the serving
tier's job is to *coalesce* concurrent single requests into one padded
device dispatch (``CompiledModel.run_many``) without retracing and
without letting a burst melt the queue. Three pieces:

- :mod:`~paddle_tpu.serving.batcher` — the dynamic micro-batcher: a
  bounded request queue feeding a dispatch loop that stacks
  same-signature requests into fixed padding buckets (so ``lax.scan``
  compiles once per bucket, never per queue depth), with a max batch
  size and a batch-formation timeout as the latency/throughput knob.
- :mod:`~paddle_tpu.serving.registry` — named, versioned
  ``load_compiled`` artifacts with warm-up on load (the jit is
  pre-triggered at every bucket), atomic hot reload behind in-flight
  requests, and rollback to the serving version when a reload's warm-up
  fails (fault site ``serving.reload``).
- :mod:`~paddle_tpu.serving.admission` — queue-depth backpressure,
  per-request deadlines, and shed-on-overload, recorded through
  ``paddle_tpu.resilience`` degradation events so chaos specs cover the
  serving path.

:class:`~paddle_tpu.serving.service.InferenceService` ties them together
in-process; :mod:`~paddle_tpu.serving.httpd` puts a stdlib JSON endpoint
in front of it, and ``paddle_tpu serve <artifact_dir>`` is the CLI verb.
Knobs: ``FLAGS.serve_max_batch`` / ``serve_batch_timeout_ms`` /
``serve_queue_depth``; architecture and overload semantics in
``doc/serving.md``.
"""
from __future__ import annotations

from .admission import (  # noqa: F401
    AdmissionController, DeadlineExceededError, ModelUnavailableError,
    OverloadError, ServingError,
)
from .batcher import MicroBatcher, bucket_for, padding_buckets  # noqa: F401
from .registry import ModelEntry, ModelRegistry  # noqa: F401
from .service import InferenceService  # noqa: F401
from .httpd import make_server  # noqa: F401

__all__ = [
    "InferenceService", "ModelRegistry", "ModelEntry", "MicroBatcher",
    "AdmissionController", "ServingError", "OverloadError",
    "DeadlineExceededError", "ModelUnavailableError",
    "padding_buckets", "bucket_for", "make_server",
]
