"""Online inference serving over AOT-compiled artifacts.

The reference deploys by multi-threading gradient machines behind its C
API (paddle/capi/gradient_machine.h:36
``paddle_gradient_machine_create_for_inference``) and the fluid inference
engine's ``Load`` — one request, one forward, per thread. The TPU-native
redesign inverts that: XLA wants FEW, LARGE dispatches, so the serving
tier's job is to *coalesce* concurrent single requests into one padded
device dispatch (``CompiledModel.run_many``) without retracing and
without letting a burst melt the queue. Three pieces:

- :mod:`~paddle_tpu.serving.batcher` — the dynamic micro-batcher: a
  bounded request queue feeding a dispatch loop that stacks
  same-signature requests into fixed padding buckets (so ``lax.scan``
  compiles once per bucket, never per queue depth), with a max batch
  size and a batch-formation timeout as the latency/throughput knob.
- :mod:`~paddle_tpu.serving.registry` — named, versioned
  ``load_compiled`` artifacts with warm-up on load (the jit is
  pre-triggered at every bucket), atomic hot reload behind in-flight
  requests, and rollback to the serving version when a reload's warm-up
  fails (fault site ``serving.reload``).
- :mod:`~paddle_tpu.serving.admission` — queue-depth backpressure,
  per-request deadlines, and shed-on-overload, recorded through
  ``paddle_tpu.resilience`` degradation events so chaos specs cover the
  serving path.

The autoregressive tier rides beside the micro-batcher (request-level
stacking is wrong by construction for decode — a finished sequence
would keep burning device time as padding):

- :mod:`~paddle_tpu.serving.kvcache` — the paged KV pool: fixed-size
  pages preallocated per model, per-sequence block tables, O(1) host
  alloc/free, exhaustion as policy (shed/preempt + recorded
  ``kv_pool_exhausted`` events), never a crash.
- :mod:`~paddle_tpu.serving.generator` — continuous (iteration-level)
  batching: one engine loop that admits prefills, runs ONE fused decode
  step for the whole running batch through block-table gather attention
  (compiled once — trace-free at any mix of sequence lengths), samples,
  and retires finished sequences mid-flight so their pages recycle.
  Greedy output is token-identical to sequential full-sequence decode.
- :mod:`~paddle_tpu.serving.speculative` — speculative decoding's draft
  side: a small same-vocabulary draft model (its own page pool + block
  tables) proposes k tokens per round in one dispatch, the target
  verifies all k+1 lanes in ONE fused step, and rejected lanes cost a
  page-table trim, never a cache rollback. Greedy output stays
  token-identical; any draft failure degrades to plain decode (fault
  site ``serving.speculate``), recorded, never an outage.
- :mod:`~paddle_tpu.serving.prefix` — copy-on-write prefix sharing
  over the paged pool: prefill pages are content-hashed (rolling chain
  over ``serve_page_tokens``-sized chunks) and refcounted, so N
  concurrent same-prefix requests pin ONE physical copy; the first
  divergent write copies just that page (the engine's CoW move), and
  an LRU keeps unreferenced prefix pages warm until allocation
  pressure reclaims them. Greedy output is bit-identical sharing on or
  off (fault site ``serving.prefix`` degrades to private pages).
- :mod:`~paddle_tpu.serving.disagg` — disaggregated prefill/decode
  tiers: a prefill-class :class:`~paddle_tpu.serving.disagg.
  PrefillEngine` runs only the prompt pass and exports the finished KV
  pages + request state as a :class:`~paddle_tpu.serving.disagg.
  HandoffArtifact`; :func:`~paddle_tpu.serving.disagg.ship` delivers
  it into a decode-class engine's ``submit_prefilled`` (fault site
  ``serving.ship``: a failed hop re-prefills on the decode tier —
  slower, bit-identical, never lost).

:class:`~paddle_tpu.serving.service.InferenceService` ties them together
in-process (``infer``/``infer_async`` + ``generate``/``generate_async``;
``load_model`` auto-detects compiled vs generative artifacts);
:mod:`~paddle_tpu.serving.httpd` puts a stdlib JSON endpoint in front of
it, and ``paddle_tpu serve <artifact_dir>`` is the CLI verb. Knobs:
``FLAGS.serve_max_batch`` / ``serve_batch_timeout_ms`` /
``serve_queue_depth`` / ``serve_max_running`` / ``serve_kv_pages`` /
``serve_page_tokens``; architecture and overload semantics in
``doc/serving.md``.
"""
from __future__ import annotations

from .admission import (  # noqa: F401
    AdmissionController, DeadlineExceededError, ModelUnavailableError,
    OverloadError, ServingError,
)
from .batcher import MicroBatcher, bucket_for, padding_buckets  # noqa: F401
from .kvcache import (  # noqa: F401
    BlockTable, PagePool, PoolExhausted, pages_for,
)
from .registry import ModelEntry, ModelRegistry  # noqa: F401
from .service import GenEntry, InferenceService  # noqa: F401
from .httpd import make_server  # noqa: F401
from .generator import (  # noqa: F401
    GenerationEngine, GenRequest, GenResult, reference_decode,
    sample_token,
)
from .speculative import DraftEngine  # noqa: F401
from .prefix import PrefixCache  # noqa: F401
from .disagg import HandoffArtifact, PrefillEngine, ship  # noqa: F401
from .pool import ReplicaPool, StaticPool  # noqa: F401
from .router import Router, make_router_server  # noqa: F401
from .autoscale import Autoscaler  # noqa: F401

__all__ = [
    "InferenceService", "ModelRegistry", "ModelEntry", "MicroBatcher",
    "AdmissionController", "ServingError", "OverloadError",
    "DeadlineExceededError", "ModelUnavailableError",
    "padding_buckets", "bucket_for", "make_server",
    "PagePool", "BlockTable", "PoolExhausted", "pages_for",
    "GenerationEngine", "GenRequest", "GenResult", "GenEntry",
    "reference_decode", "sample_token", "DraftEngine",
    "PrefixCache", "HandoffArtifact", "PrefillEngine", "ship",
    "ReplicaPool", "StaticPool", "Router", "make_router_server",
    "Autoscaler",
]
