"""Replica pool: spawn, supervise, and resize N ``serve`` workers.

One ``paddle_tpu serve`` process owns one batcher, one generation
engine, one KV pool — which caps throughput at a single process and
makes every crash a full outage. The pool is the supervision half of
the router tier (the reference ran this in Go: the master and pservers
registered in etcd and watched each other's health; here the pool IS
the watcher): it spawns ``n`` identical ``serve`` subprocesses on free
ports, reads each one's readiness line for the bound port, and treats
worker death the way the elastic supervisor treats trainer death — as
an event to classify and absorb, never a verdict. The classification
arithmetic itself (restart budget, crash-loop window, backoff,
generation bump, grace escalation) lives in the ONE shared
:mod:`paddle_tpu.resilience.supervise` core both supervisors consume:

- an unexpected exit (crash, OOM, an operator's ``kill -9``) restarts
  that replica on the resilience :class:`RetryPolicy` backoff schedule,
  spending a per-replica ``restart_budget``; every restart is a
  recorded ``router_replica_restart`` degradation event, and the
  restarted worker comes back on a FRESH port (the router re-discovers
  it through :meth:`ReplicaPool.snapshot`). A respawn that stays up
  ``budget_reset_s`` (default 60 s) resets the slot's record — the
  budget bounds crash loops, not the fleet's lifetime crash total;
- a spent budget marks the replica **lost** (``router_replica_lost``
  event) — the remaining replicas keep serving, the pool never raises;
- :meth:`ReplicaPool.stop` drains the fleet with the shared
  escalation: SIGTERM (each worker's ``serve`` loop drains in-flight
  requests and exits 0), then SIGKILL after ``grace_sec`` — a worker
  wedged in a bad compile cannot hold the pool hostage. A restart
  backoff pending at stop time is CANCELLED (the sleep rides a stop
  event), so a closing pool can never spawn an orphan worker.

The fleet is elastic at run time: :meth:`grow` adds a slot (the
autoscaler's scale-up), :meth:`shrink` retires one — an EXPECTED exit
the monitor will not respawn — with the same grace escalation (the
autoscaler's drain-first scale-down). Every membership change (grow,
shrink, restart respawn, lost) fires the registered ``on_membership``
listeners so the router's poller picks up new and drained replicas
mid-flight instead of at its next timer tick. All membership mutation
serializes on ``membership_lock`` — the one lock the rolling reload
and the autoscaler share, so a shrink can never land mid-rollout.

The pool knows nothing about HTTP routing; it only answers "which
worker processes exist right now, and are they ready". The router
(:mod:`paddle_tpu.serving.router`) polls :meth:`snapshot` and layers
health, load scoring, and failover on top.
"""
from __future__ import annotations

import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time

from ..resilience import RetryPolicy, record_durable_event
from ..resilience.supervise import (SlotSupervision, escalate_stop,
                                    signal_quietly)
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["Replica", "ReplicaPool", "StaticReplica", "StaticPool"]


def _repo_root():
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


class Replica(object):
    """One live ``serve`` worker: process handle + readiness state.

    ``generation`` counts respawns of this slot (0 = the original
    process); the router resets its per-replica health state whenever
    the generation it sees changes — a fresh process must not inherit
    its predecessor's eject record.
    """

    __slots__ = ("index", "generation", "proc", "host", "port", "info",
                 "_ready", "_reader", "last_line")

    def __init__(self, index, generation, proc, host):
        self.index = index
        self.generation = generation
        self.proc = proc
        self.host = host
        self.port = None
        self.info = None          # the readiness line's {"serving": ...}
        self.last_line = None     # most recent stdout JSON (stop stats)
        self._ready = threading.Event()
        self._reader = threading.Thread(
            target=self._read_stdout, daemon=True,
            name="paddle_tpu-replica-%d-stdout" % index)
        self._reader.start()

    def _read_stdout(self):
        """Parse the worker's stdout: the first ``{"serving": ...}``
        line carries the bound port (the ``serve`` readiness contract);
        everything after is drained so a chatty worker can never block
        on a full pipe, and the last JSON line is kept (the
        ``serving_stopped`` evidence)."""
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            self.last_line = obj
            if "serving" in obj and not self._ready.is_set():
                self.info = obj["serving"]
                self.port = int(self.info["port"])
                self._ready.set()

    @property
    def pid(self):
        return self.proc.pid

    @property
    def alive(self):
        return self.proc.poll() is None

    @property
    def ready(self):
        return self.alive and self._ready.is_set()

    @property
    def base_url(self):
        if self.port is None:
            return None
        return "http://%s:%d" % (self.host, self.port)

    def wait_ready(self, timeout):
        """Block until the readiness line arrives; False on timeout or
        if the process died first."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._ready.wait(timeout=0.2):
                return True
            if not self.alive:
                return False
        return self._ready.is_set()

    def signal(self, signum):
        signal_quietly(self.proc, signum)


class ReplicaPool(object):
    """Spawn and supervise ``n`` ``paddle_tpu serve`` workers.

    ``serve_args`` is the extra argv every worker gets (``--max_batch``,
    ``--extra_model name=dir``, ...); ``env_overrides`` maps replica
    index -> extra env vars for THAT worker (how the load harness arms
    a fault spec in exactly one replica — including a slot the
    autoscaler will only grow into later); ``serve_args_overrides``
    maps replica index -> extra argv appended after ``serve_args`` for
    THAT worker — how a disaggregated fleet gives each slot its tier
    (``--tier prefill`` / ``--tier decode``) while sharing the rest of
    the deployment config. Overrides stick to the SLOT: a crash-restart
    respawns with the same tier. Ports are always ``--port 0`` — each
    worker binds a free one and reports it on the readiness line.
    """

    def __init__(self, artifact_dir, n, name="default", host="127.0.0.1",
                 serve_args=None, env=None, env_overrides=None,
                 serve_args_overrides=None, restart_budget=None,
                 grace_sec=5.0, ready_timeout=180.0,
                 budget_reset_s=60.0, python=None):
        from ..flags import FLAGS
        if n < 1:
            raise ValueError("replica count must be >= 1, got %d" % n)
        self.artifact_dir = artifact_dir
        self.n = int(n)
        self.name = name
        self.host = host
        self.serve_args = list(serve_args or [])
        self.env_overrides = dict(env_overrides or {})
        self.serve_args_overrides = {
            int(i): list(v) for i, v in (serve_args_overrides or {}).items()}
        self.restart_budget = int(
            restart_budget if restart_budget is not None
            else FLAGS.route_restart_budget)
        self.grace_sec = float(grace_sec)
        self.ready_timeout = float(ready_timeout)
        self.budget_reset_s = float(budget_reset_s)
        self.python = python or sys.executable
        self.base_env = dict(env if env is not None else os.environ)
        # the workers import paddle_tpu with `python -m`: the repo root
        # must be importable regardless of the supervisor's own cwd
        root = _repo_root()
        pp = self.base_env.get("PYTHONPATH", "")
        if root not in pp.split(os.pathsep):
            self.base_env["PYTHONPATH"] = (root + os.pathsep + pp if pp
                                           else root)
        self._lock = _locks.make_lock("serving.pool.state")
        # membership mutation (grow/shrink/rolling-reload) serializes
        # here — NOT on _lock, which protects the fast bookkeeping: a
        # shrink holds membership_lock for its whole drain window
        self.membership_lock = _locks.make_rlock("serving.pool.membership")
        self._replicas = [None] * self.n      # index -> Replica
        self._retired = [False] * self.n      # shrunk slots: no respawn
        self._sup = SlotSupervision(
            self.restart_budget,
            retry=RetryPolicy(max_attempts=self.restart_budget + 1,
                              backoff=0.25, multiplier=2.0,
                              max_backoff=5.0, jitter=0.1, seed=0,
                              name="router.replica_restart"))
        self._exits = queue.Queue()           # (index, generation, rc)
        self._closing = False
        self._stop_event = threading.Event()  # cancels pending backoffs
        self._listeners = []                  # membership-change callbacks
        self._monitor = None

    # -- membership listeners ------------------------------------------------
    def on_membership(self, fn):
        """Register a zero-arg callback fired after every membership
        change (grow/shrink/restart-respawn/lost) — the router hooks
        its poll wake-up here so a change is seen mid-flight, not at
        the next timer tick."""
        self._listeners.append(fn)

    def _notify_membership(self):
        for fn in list(self._listeners):
            try:
                fn()
            except Exception:
                pass   # a listener's glitch must never stall supervision

    # -- spawning ------------------------------------------------------------
    def _spawn(self, index, generation):
        argv = [self.python, "-m", "paddle_tpu", "serve",
                self.artifact_dir, "--name", self.name,
                "--host", self.host, "--port", "0"] + self.serve_args \
            + self.serve_args_overrides.get(index, [])
        env = dict(self.base_env)
        env.update(self.env_overrides.get(index, {}))
        proc = subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                                text=True)
        rep = Replica(index, generation, proc, self.host)
        threading.Thread(target=self._reap, args=(rep,), daemon=True,
                         name="paddle_tpu-replica-%d-wait" % index).start()
        return rep

    def _reap(self, rep):
        self._exits.put((rep.index, rep.generation, rep.proc.wait()))

    def start(self, wait=True):
        """Spawn the fleet; with ``wait`` (default), block until every
        replica's readiness line arrives — raising RuntimeError (after
        stopping the fleet) if any worker dies or times out before
        becoming ready, with its index named."""
        from .. import profiler as _prof
        _prof.update_router_counters(router_replicas=self.n)
        try:
            with self._lock:
                for i in range(self.n):
                    self._replicas[i] = self._spawn(i, 0)
        except Exception:
            # a failed Popen partway through (fork ENOMEM, bad
            # interpreter) must not orphan the workers already running
            self.stop()
            raise
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="paddle_tpu-pool-monitor")
        self._monitor.start()
        if wait:
            for i, rep in enumerate(list(self._replicas)):
                if not rep.wait_ready(self.ready_timeout):
                    rc = rep.proc.poll()
                    self.stop()
                    raise RuntimeError(
                        "replica %d never became ready (%s) — check the "
                        "worker's stderr above" %
                        (i, "exit code %s" % rc if rc is not None
                         else "timeout after %.0fs" % self.ready_timeout))
        return self

    # -- supervision ---------------------------------------------------------
    def _monitor_loop(self):
        """Classify exits: during shutdown they are expected, and so is
        the exit of a slot :meth:`shrink` retired; otherwise restart on
        the shared supervision budget, then declare the slot lost. Runs
        until ``stop()`` flips ``_closing`` and the queue drains."""
        from .. import profiler as _prof
        while True:
            try:
                index, generation, rc = self._exits.get(timeout=0.2)
            except queue.Empty:
                if self._closing:
                    return
                continue
            with self._lock:
                if self._closing:
                    continue
                if self._retired[index]:
                    continue      # shrink's expected exit, not a crash
                current = self._replicas[index]
                if current is None or current.generation != generation:
                    continue      # stale exit of an already-replaced proc
                decision = self._sup.classify_exit(index)
                if decision.action == "lost":
                    record_durable_event("router_replica_lost", site="serving.route",
                                 replica=index, rc=rc,
                                 restarts_used=decision.used)
                    _prof.update_router_counters(router_replica_lost=1)
                    lost = True
                else:
                    lost = False
            if lost:
                self._notify_membership()
                continue
            record_durable_event("router_replica_restart", site="serving.route",
                         replica=index, rc=rc, attempt=decision.attempt,
                         backoff_sec=round(decision.backoff_sec, 3))
            _prof.update_router_counters(router_replica_restarts=1)
            # the backoff sleeps on its own thread: one replica's
            # backoff must not delay the monitor's classification (and
            # respawn) of every OTHER dead replica behind it in the
            # queue
            threading.Thread(
                target=self._respawn_after,
                args=(index, current, decision.backoff_sec), daemon=True,
                name="paddle_tpu-replica-%d-respawn" % index).start()

    def _respawn_after(self, index, dead, delay):
        # the backoff rides the stop event, NOT time.sleep: stop() (or
        # a shrink retiring this slot) cancels the pending respawn
        # instead of letting it fire into a closing pool and orphan a
        # serve worker. ``dead`` is the replica whose exit scheduled
        # this respawn: if the slot holds anything else by wake-up
        # time (a shrink retired it and a later grow() RECYCLED the
        # index), the respawn is stale — spawning would overwrite the
        # recycled worker and orphan it
        if self._stop_event.wait(delay):
            return
        with self._lock:
            if self._closing or self._retired[index]:
                return
            if self._replicas[index] is not dead:
                return
            rep = self._spawn(index, self._sup.bump_generation(index))
            self._replicas[index] = rep
        self._notify_membership()
        threading.Thread(
            target=self._maybe_reset_budget, args=(rep,), daemon=True,
            name="paddle_tpu-replica-%d-budget" % index).start()

    def _maybe_reset_budget(self, rep):
        """A respawn that stays up ``budget_reset_s`` earns the slot a
        clean restart record — the budget bounds crash LOOPS, not the
        lifetime total: a long-running fleet must not march to lost
        replicas on one recoverable crash a week (the systemd
        StartLimitIntervalSec / erlang supervisor convention)."""
        if self._stop_event.wait(self.budget_reset_s):
            return
        with self._lock:
            if (not self._closing and rep.alive
                    and self._replicas[rep.index] is rep):
                self._sup.note_stable(rep.index)

    # -- elastic membership --------------------------------------------------
    def grow(self, extra_args=None):
        """Add one slot to the fleet (the autoscaler's scale-up):
        recycle the lowest retired (cleanly shrunk, not lost) slot if
        one exists — an oscillating up/down/up fleet must not grow the
        slot table without bound — else spawn at the next index. The
        recycled slot comes back on a bumped generation (any stale
        state keyed on the old one resets) with a clean restart
        record, supervised exactly like the original fleet. Does NOT
        wait for readiness — the caller watches the returned
        :class:`Replica` (the autoscaler's warm-up window).
        ``extra_args`` (a tiered autoscaler's ``--tier <class>``)
        becomes the slot's ``serve_args_overrides`` entry — sticky
        across crash-restarts, REPLACING whatever a previously retired
        occupant of a recycled slot had. Returns the new replica."""
        from .. import profiler as _prof
        with self.membership_lock:
            with self._lock:
                if self._closing:
                    raise RuntimeError("pool is stopped")
                index = None
                for i, retired in enumerate(self._retired):
                    if retired and not self._sup.is_lost(i):
                        index = i
                        break
                appended = index is None
                if appended:
                    index = len(self._replicas)
                    self._replicas.append(None)
                    self._retired.append(False)
                    self.n = len(self._replicas)
                    generation = 0
                else:
                    self._retired[index] = False
                    self._sup.note_stable(index)
                    generation = self._sup.bump_generation(index)
                prev_override = self.serve_args_overrides.get(index)
                if extra_args is not None:
                    self.serve_args_overrides[index] = list(extra_args)
                elif not appended:
                    # recycled slot, no explicit args: drop the retired
                    # occupant's override rather than resurrecting a
                    # tier nobody asked for
                    self.serve_args_overrides.pop(index, None)
                try:
                    rep = self._spawn(index, generation)
                except Exception:
                    # a failed Popen must not corrupt the slot table:
                    # un-append the fresh slot, or put a recycled one
                    # back in the retired (re-recyclable) state
                    if appended:
                        self._replicas.pop()
                        self._retired.pop()
                        self.n = len(self._replicas)
                    else:
                        self._retired[index] = True
                    if prev_override is None:
                        self.serve_args_overrides.pop(index, None)
                    else:
                        self.serve_args_overrides[index] = prev_override
                    raise
                self._replicas[index] = rep
                active = self._active_count_locked()
        record_durable_event("router_replica_added", site="serving.route",
                     replica=index, pid=rep.pid)
        _prof.update_router_counters(router_replicas=active)
        self._notify_membership()
        return rep

    def _active_count_locked(self):
        return sum(1 for i, r in enumerate(self._replicas)
                   if r is not None and not self._sup.is_lost(i)
                   and not self._retired[i])

    def shrink(self, index, grace_sec=None):
        """Retire slot ``index`` (the autoscaler's drain-first
        scale-down): mark it retired FIRST — its exit is expected, the
        monitor will not respawn it and a pending restart backoff is
        abandoned — then drain the worker with the shared SIGTERM ->
        SIGKILL escalation. Returns the worker's exit code (None if the
        slot had no live process)."""
        with self.membership_lock:
            with self._lock:
                if not 0 <= index < len(self._replicas):
                    raise IndexError("no replica slot %d" % index)
                self._retired[index] = True
                rep = self._replicas[index]
            rc = None
            if rep is not None and rep.proc.poll() is None:
                rc = escalate_stop(
                    [(index, rep.proc)],
                    self.grace_sec if grace_sec is None else grace_sec,
                ).get(index)
            elif rep is not None:
                rc = rep.proc.poll()
        record_durable_event("router_replica_retired", site="serving.route",
                     replica=index, rc=rc)
        self._notify_membership()
        return rc

    def slot_info(self, index):
        """One slot's supervision state — what the autoscaler's warm-up
        watch reads (a generation bump or a lost mark inside the
        warm-up window is a crash-looping scale-up)."""
        with self._lock:
            rep = (self._replicas[index]
                   if 0 <= index < len(self._replicas) else None)
            return {
                "exists": rep is not None,
                "generation": rep.generation if rep is not None else None,
                "alive": bool(rep is not None and rep.alive),
                "ready": bool(rep is not None and rep.ready),
                "lost": self._sup.is_lost(index),
                "retired": (self._retired[index]
                            if 0 <= index < len(self._retired) else True),
            }

    # -- the router's view ---------------------------------------------------
    def snapshot(self):
        """Current replica list (lost and retired slots excluded) — the
        router polls this; a restarted worker shows up with a bumped
        generation and a fresh port, a grown one at a new index."""
        with self._lock:
            return [r for i, r in enumerate(self._replicas)
                    if r is not None and not self._sup.is_lost(i)
                    and not self._retired[i]]

    def describe(self):
        with self._lock:
            indices = range(len(self._replicas))
            return {
                "replicas": self.n,
                "active": self._active_count_locked(),
                "lost": self._sup.lost_slots(),
                "retired": [i for i in indices if self._retired[i]],
                "restarts_used": self._sup.used_map(indices),
                "workers": [
                    {"index": r.index, "generation": r.generation,
                     "pid": r.pid, "port": r.port, "ready": r.ready,
                     "retired": self._retired[r.index]}
                    for r in self._replicas if r is not None],
            }

    def kill(self, index, signum=signal.SIGKILL):
        """Send ``signum`` to replica ``index`` (the chaos harness's
        aim point — a SIGKILL here exercises the restart path)."""
        with self._lock:
            rep = self._replicas[index]
        if rep is not None:
            rep.signal(signum)
        return rep.pid if rep is not None else None

    # -- shutdown ------------------------------------------------------------
    def stop(self):
        """SIGTERM the fleet (each worker drains and exits 0), escalate
        to SIGKILL after ``grace_sec``; pending restart backoffs are
        cancelled. Returns {index: rc}."""
        with self._lock:
            self._closing = True
            self._stop_event.set()
            reps = [r for r in self._replicas if r is not None]
        rcs = escalate_stop(((r.index, r.proc) for r in reps),
                            self.grace_sec)
        if self._monitor is not None and self._monitor.is_alive():
            self._monitor.join(timeout=5.0)
        return rcs

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class StaticReplica(object):
    """A pool entry for an externally-managed worker (tests, or replicas
    someone else supervises — e.g. k8s pods behind fixed addresses)."""

    __slots__ = ("index", "generation", "host", "port")

    def __init__(self, index, host, port, generation=0):
        self.index = index
        self.generation = generation
        self.host = host
        self.port = int(port)

    alive = True
    ready = True
    pid = None

    @property
    def base_url(self):
        return "http://%s:%d" % (self.host, self.port)


class StaticPool(object):
    """Route over a fixed address list instead of supervised
    subprocesses: ``StaticPool(["127.0.0.1:8500", ...])``. No restarts
    — a dead address is the router's eject machinery's problem — and no
    autoscaling (grow/shrink raise: someone else owns the membership);
    ``membership_lock`` still exists so the rolling reload serializes
    the same way over either pool kind."""

    def __init__(self, addresses):
        self.membership_lock = _locks.make_rlock(
            "serving.pool.membership")
        self._replicas = []
        for i, addr in enumerate(addresses):
            host, _, port = str(addr).rpartition(":")
            self._replicas.append(
                StaticReplica(i, host or "127.0.0.1", int(port)))

    def on_membership(self, fn):
        pass   # static membership never changes

    def snapshot(self):
        return list(self._replicas)

    def describe(self):
        return {"replicas": len(self._replicas), "lost": [],
                "workers": [{"index": r.index, "port": r.port,
                             "generation": r.generation, "ready": True}
                            for r in self._replicas]}

    def kill(self, index, signum=None):
        raise RuntimeError("StaticPool does not own its workers")

    def grow(self, extra_args=None):
        raise RuntimeError("StaticPool does not own its membership")

    def shrink(self, index, grace_sec=None):
        raise RuntimeError("StaticPool does not own its membership")

    def stop(self):
        return {}
