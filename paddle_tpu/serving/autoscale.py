"""Closed-loop autoscaling on the router's pressure signal.

PR 10 built the sensing half of "serving at planet scale": the router
exposes per-model ``pressure = backlog/capacity + shed_rate`` in
RouterStats. This module is the acting half — the reference's Go
master/etcd runtime existed so a fleet could grow, shrink, and lose
members without an operator in the loop; here one controller thread
closes that loop over the :class:`~paddle_tpu.serving.pool.ReplicaPool`
within a ``[min_replicas, max_replicas]`` budget. Four defenses keep a
feedback loop from becoming the outage:

**Hysteresis + flap guard.** Decisions read only the EWMA-SMOOTHED
pressure (:meth:`Router.pressure_smoothed` — a single poll spike can
neither trigger a scale-up nor mask a sustained overload). Scale-up
needs the signal to hold at or above ``up_pressure`` for ``k_up``
CONSECUTIVE control ticks; scale-down needs it at or below the (lower)
``down_pressure`` for the longer ``quiet_polls`` window. Each
direction then has its own cooldown (``cooldown_s`` up,
``down_cooldown_s`` down, default 2x), and a scale-down additionally
waits out ``down_cooldown_s`` since the LAST scale-up — oscillating
load lands in the dead band between the thresholds and cannot thrash
the fleet. One decision per tick, and never while a previous scale-up
is still warming.

**Drain-first scale-down.** The victim (the highest-index active slot
— last grown, first retired) is marked ``draining`` in the router so
no new work routes to it, the controller waits for the router-tracked
in-flight count to reach zero (or ``drain_deadline_s``), and only then
retires the slot through :meth:`ReplicaPool.shrink` — the shared
SIGTERM -> SIGKILL escalation, under which the worker's ``serve`` loop
drains its own queue before exiting. No request is ever lost to a
policy decision. The whole sequence holds the pool's
``membership_lock``, so a rolling reload can never interleave with a
shrink.

**Crash-loop circuit breaker.** Every scale-up is watched through a
``warmup_s`` window: if the fresh replica dies inside it (the pool
respawning it — a generation bump — or marking it lost, or it never
reports ready), the breaker OPENS (recorded ``autoscale_breaker_open``)
and the controller refuses further scale-ups: a bad artifact or a
poisoned host must not march the budget to ``max_replicas`` worth of
crash loops. After ``breaker_backoff_s`` the breaker goes HALF-OPEN
and allows exactly one probe scale-up: a probe that warms closes the
breaker (``autoscale_breaker_close``), a probe that dies re-opens it.
The crash-looping slot itself is retired so the pool stops burning
restart budget on it.

**Degrade, never die.** The control tick is fault site
``serving.autoscale``: ANY controller failure (armed or real) records
``autoscale_degraded`` and freezes the fleet at its current size — the
router keeps serving; a dead autoscaler is a sizing regression, not an
outage.

**Per-tier mode.** A disaggregated fleet runs one controller PER
serving class (``Autoscaler(..., tier="prefill")`` /
``tier="decode"``), each acting on the class-correct signal from
:meth:`Router.tier_signal` instead of the fleet-wide pressure: the
prefill tier scales on mean queue depth per replica (prompt passes
arrive as a queue; threshold ``FLAGS.route_prefill_up_queue``), the
decode tier on mean KV page-pool occupancy (decode capacity IS page
inventory; threshold ``FLAGS.route_decode_up_frac``). A tiered
controller counts, grows (``pool.grow(extra_args=["--tier", ...])`` —
the tier rides the slot's serve-args override, sticky across
restarts), and shrinks ONLY its own class; the min/max budget is per
tier. Everything else — hysteresis, drain-first, breaker, degrade —
is identical.

Decisions surface in RouterStats (``/statz`` -> ``autoscale``), in
``resilience.events()`` (``autoscale_up`` / ``autoscale_down`` /
breaker events), and in ``profiler.autoscale_counters()`` + the
timeline artifact's ``autoscale`` section. CLI: ``paddle_tpu route
--autoscale --min_replicas 1 --max_replicas 4 [--scale_up_pressure
1.0 --scale_down_pressure 0.2 --cooldown_s 30]``; tiered:
``paddle_tpu route --tiers prefill=1,decode=2 --autoscale``.
"""
from __future__ import annotations

import threading
import time

from ..resilience import fault_point, record_durable_event
# the shared lock constructor (lock-order race detector under
# PADDLE_TPU_SANITIZE=locks)
from ..analysis import locks as _locks

__all__ = ["Autoscaler"]


class Autoscaler(object):
    """The control loop. ``router`` supplies the smoothed signal and
    the drain handles; ``pool`` must own its membership
    (:class:`ReplicaPool` — a :class:`StaticPool` raises on grow).

    Tunables default from flags: ``up_pressure``
    (FLAGS.route_scale_up_pressure), ``down_pressure``
    (FLAGS.route_scale_down_pressure), ``cooldown_s``
    (FLAGS.route_cooldown_s; ``down_cooldown_s`` defaults to 2x).
    ``clock``/``sleep`` are injectable so the whole state machine is
    testable without real waiting (the RetryPolicy convention).
    """

    def __init__(self, router, pool, min_replicas=1, max_replicas=None,
                 up_pressure=None, down_pressure=None, k_up=3,
                 quiet_polls=10, cooldown_s=None, down_cooldown_s=None,
                 poll_s=None, warmup_s=60.0, breaker_backoff_s=30.0,
                 drain_deadline_s=30.0, clock=time.monotonic,
                 sleep=time.sleep, tier=None):
        from ..flags import FLAGS
        self.router = router
        self.pool = pool
        if tier is not None and tier not in ("prefill", "decode"):
            raise ValueError("tier must be None, 'prefill' or 'decode', "
                             "got %r" % tier)
        self.tier = tier
        if tier is not None:
            # class-correct threshold defaults: the signal's UNITS
            # differ per tier (queue depth vs occupancy fraction), so
            # the fleet-wide pressure defaults would be nonsense here
            if up_pressure is None:
                up_pressure = (FLAGS.route_prefill_up_queue
                               if tier == "prefill"
                               else FLAGS.route_decode_up_frac)
            if down_pressure is None:
                down_pressure = float(up_pressure) / 4.0
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else max(self.min_replicas,
                                         FLAGS.route_replicas))
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1, got %d"
                             % self.min_replicas)
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas (%d) must be >= min_replicas "
                             "(%d)" % (self.max_replicas,
                                       self.min_replicas))
        self.up_pressure = float(
            up_pressure if up_pressure is not None
            else FLAGS.route_scale_up_pressure)
        self.down_pressure = float(
            down_pressure if down_pressure is not None
            else FLAGS.route_scale_down_pressure)
        if not self.down_pressure < self.up_pressure:
            raise ValueError(
                "hysteresis wants down_pressure (%g) < up_pressure (%g)"
                % (self.down_pressure, self.up_pressure))
        self.k_up = int(k_up)
        self.quiet_polls = int(quiet_polls)
        if self.k_up < 1 or self.quiet_polls < 1:
            raise ValueError("k_up and quiet_polls must be >= 1")
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else FLAGS.route_cooldown_s)
        self.down_cooldown_s = float(
            down_cooldown_s if down_cooldown_s is not None
            else 2.0 * self.cooldown_s)
        self.poll_s = float(poll_s if poll_s is not None
                            else max(router.poll_s, 0.05))
        self.warmup_s = float(warmup_s)
        self.breaker_backoff_s = float(breaker_backoff_s)
        self.drain_deadline_s = float(drain_deadline_s)
        self._clock = clock
        self._sleep = sleep
        self._lock = _locks.make_lock("serving.autoscale.state")
        self._up_streak = 0
        self._quiet_streak = 0
        self._last_up_t = None
        self._last_down_t = None
        self._pending = {}     # index -> {"gen", "deadline", "probe"}
        self._breaker = "closed"
        self._breaker_until = None
        self._counts = {}
        self._decisions = []   # bounded trail for /statz
        self._last_signal = None
        self._degraded = False
        self._degraded_error = None
        self._closed = False
        self._wake = threading.Event()
        self._thread = None

    # -- bookkeeping ---------------------------------------------------------
    def _count(self, key, n=1):
        from .. import profiler as _prof
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
        _prof.update_autoscale_counters(**{key: n})

    def _decision(self, action, **info):
        with self._lock:
            self._decisions.append(dict(info, action=action,
                                        t=round(self._clock(), 3)))
            del self._decisions[:-32]

    def _active(self):
        """The committed fleet size: live slots, a still-warming grow
        included (it is capacity the budget already spent). A tiered
        controller counts only its OWN class — a replica whose tier
        the router has not learned yet counts iff this controller grew
        it (it is in the warm-up watch)."""
        reps = self.pool.snapshot()
        if self.tier is None:
            return len(reps)
        with self._lock:
            pending = set(self._pending)
        n = 0
        for r in reps:
            t = self.router.replica_tier(r.index)
            if t == self.tier or (not t and r.index in pending):
                n += 1
        return n

    def signal(self):
        """The control signal. Fleet-wide mode: the max per-model
        smoothed pressure (the fleet is homogeneous — every replica
        serves every model, so the hottest model sizes the pool); None
        before the first poll. Tiered mode: the router's per-class
        signal (queue depth for prefill, page occupancy for decode)."""
        if self.tier is not None:
            return self.router.tier_signal(self.tier)
        vals = self.router.pressure_smoothed()
        if not vals:
            return None
        return max(vals.values())

    # -- the control tick ----------------------------------------------------
    def tick(self):
        """One control decision. Any failure inside — the armed
        ``serving.autoscale`` site or a real bug — degrades the
        controller to a FIXED fleet with a recorded event; the router
        never dies with it."""
        if self._degraded or self._closed:
            return
        try:
            fault_point("serving.autoscale")
            self._tick_inner()
        except Exception as e:
            from .. import profiler as _prof
            self._degraded = True
            self._degraded_error = repr(e)
            record_durable_event("autoscale_degraded", site="serving.autoscale",
                         error=repr(e), replicas=self._safe_active())
            _prof.update_autoscale_counters(autoscale_degraded=1)

    def _safe_active(self):
        try:
            return self._active()
        except Exception:
            return None

    def _tick_inner(self):
        from .. import profiler as _prof
        now = self._clock()
        self._check_warmups(now)
        sig = self.signal()
        self._last_signal = sig
        _prof.update_autoscale_counters(autoscale_ticks=1)
        if sig is not None:
            _prof.update_autoscale_counters(autoscale_pressure_max=sig)
        if sig is None:
            return
        # streaks: CONSECUTIVE ticks on one side of a threshold. The
        # dead band between down_pressure and up_pressure resets both —
        # oscillating load never accumulates either decision.
        self._up_streak = self._up_streak + 1 \
            if sig >= self.up_pressure else 0
        self._quiet_streak = self._quiet_streak + 1 \
            if sig <= self.down_pressure else 0
        if self._pending:
            return    # a scale-up is still warming: one change at a time
        active = self._active()
        # floor reconciliation: a replica the pool declared LOST (spent
        # restart budget) drops the fleet below min_replicas with no
        # pressure required to notice — the floor is a guarantee, not a
        # threshold. Rides the same cooldown and breaker gates as a
        # pressure scale-up (a crash-looping artifact must not fight
        # the floor forever).
        if (active < self.min_replicas
                and self._cooled(now, self._last_up_t, self.cooldown_s)):
            if not self._breaker_allows(now):
                self._count("autoscale_breaker_refused")
                return
            self._scale_up(now, sig, active, reason="floor")
            return
        if (self._up_streak >= self.k_up
                and active < self.max_replicas
                and self._cooled(now, self._last_up_t, self.cooldown_s)):
            if not self._breaker_allows(now):
                self._count("autoscale_breaker_refused")
                return
            self._scale_up(now, sig, active)
            return        # one decision per tick
        if (self._quiet_streak >= self.quiet_polls
                and active > self.min_replicas
                and self._cooled(now, self._last_down_t,
                                 self.down_cooldown_s)
                and self._cooled(now, self._last_up_t,
                                 self.down_cooldown_s)):
            self._scale_down(now, sig, active)

    @staticmethod
    def _cooled(now, last_t, cooldown):
        return last_t is None or (now - last_t) >= cooldown

    # -- breaker -------------------------------------------------------------
    def _breaker_allows(self, now):
        if self._breaker == "closed":
            return True
        if self._breaker == "open":
            if self._breaker_until is not None \
                    and now >= self._breaker_until:
                self._breaker = "half_open"
                record_durable_event("autoscale_breaker_half_open",
                             site="serving.autoscale")
                self._count("autoscale_breaker_half_opens")
                return True     # this tick's scale-up is the probe
            return False
        # half_open with no pending probe (the probe resolved the tick
        # it was watched): allow another probe
        return True

    def _breaker_open(self, now, replica, reason):
        self._breaker = "open"
        self._breaker_until = now + self.breaker_backoff_s
        record_durable_event("autoscale_breaker_open", site="serving.autoscale",
                     replica=replica, reason=reason,
                     backoff_s=self.breaker_backoff_s)
        self._count("autoscale_breaker_opens")
        self._decision("breaker_open", replica=replica, reason=reason)

    def _check_warmups(self, now):
        """Watch every scale-up through its warm-up window: ready in
        time closes the loop (and the breaker, for a probe); a death —
        the pool respawned it (generation bump), marked it lost, or the
        process is simply gone — or a warm-up timeout opens the
        breaker and retires the crash-looping slot."""
        for index in list(self._pending):
            p = self._pending[index]
            info = self.pool.slot_info(index)
            died = (info["lost"] or info["retired"]
                    or (info["generation"] is not None
                        and info["generation"] > p["gen"])
                    or (info["exists"] and not info["alive"]))
            if info["ready"] and not died:
                with self._lock:
                    del self._pending[index]
                if p["probe"] or self._breaker != "closed":
                    self._breaker = "closed"
                    self._breaker_until = None
                    record_durable_event("autoscale_breaker_close",
                                 site="serving.autoscale", replica=index)
                    self._count("autoscale_breaker_closes")
                self._decision("warmed", replica=index)
                continue
            reason = None
            if died:
                reason = "lost" if info["lost"] else "died_in_warmup"
            elif now >= p["deadline"]:
                reason = "warmup_timeout"
            if reason is None:
                continue    # still booting, window open
            with self._lock:
                del self._pending[index]
            self._breaker_open(now, index, reason)
            # stop the pool burning restart budget on a crash loop the
            # breaker already judged; shrink is idempotent on a lost
            # slot (the process is gone either way)
            if not info["retired"]:
                try:
                    self.pool.shrink(index)
                except Exception:
                    pass    # already lost/stopped: the retire is moot
            self.router.forget(index)

    # -- decisions -----------------------------------------------------------
    def _scale_up(self, now, sig, active, reason="pressure"):
        from .. import profiler as _prof
        probe = self._breaker == "half_open"
        # only a tiered controller needs the override plumbing — the
        # plain call keeps every duck-typed pool (tests, StaticPool
        # raising) working unchanged
        rep = (self.pool.grow(extra_args=["--tier", self.tier])
               if self.tier else self.pool.grow())
        with self._lock:
            self._pending[rep.index] = {"gen": rep.generation,
                                        "deadline": now + self.warmup_s,
                                        "probe": probe}
        self._up_streak = 0
        self._quiet_streak = 0
        self._last_up_t = now
        record_durable_event("autoscale_up", site="serving.autoscale",
                     replica=rep.index, pressure=sig, reason=reason,
                     replicas_from=active, replicas_to=active + 1,
                     probe=probe)
        self._count("autoscale_ups")
        _prof.update_autoscale_counters(autoscale_replicas=active + 1)
        self._decision("up", replica=rep.index, pressure=sig,
                       replicas=active + 1, probe=probe, reason=reason)

    def _pick_victim(self):
        reps = self.pool.snapshot()
        if self.tier is not None:
            # a tiered controller retires only its OWN class — the
            # decode tier idling must never shrink a prefill replica
            reps = [r for r in reps
                    if self.router.replica_tier(r.index) == self.tier]
        if not reps:
            return None
        return max(reps, key=lambda r: r.index).index

    def _scale_down(self, now, sig, active):
        from .. import profiler as _prof
        # the whole drain+retire holds the pool's ONE membership lock:
        # a rolling reload serializes against it instead of probing the
        # replica we are draining
        with self.pool.membership_lock:
            victim = self._pick_victim()
            if victim is None or self._active() <= self.min_replicas:
                return     # membership changed while we waited the lock
            self.router.set_draining(victim, True)
            drained = self._await_drain(victim)
            inflight = self.router.replica_inflight(victim)
            rc = self.pool.shrink(victim)
        self.router.forget(victim)
        self._up_streak = 0
        self._quiet_streak = 0
        self._last_down_t = self._clock()
        record_durable_event("autoscale_down", site="serving.autoscale",
                     replica=victim, pressure=sig,
                     replicas_from=active, replicas_to=active - 1,
                     drained=drained, inflight_at_stop=inflight, rc=rc)
        self._count("autoscale_downs")
        _prof.update_autoscale_counters(autoscale_replicas=active - 1)
        self._decision("down", replica=victim, pressure=sig,
                       replicas=active - 1, drained=drained)

    def _await_drain(self, index):
        """Wait for the router-tracked in-flight count at ``index`` to
        reach zero, bounded by ``drain_deadline_s``. The slot is
        already draining, so the count only falls. True = fully
        drained; False = deadline hit (the worker's own SIGTERM drain
        still runs — the escalation window is the second net)."""
        deadline = self._clock() + self.drain_deadline_s
        while self._clock() < deadline:
            if self.router.replica_inflight(index) <= 0:
                return True
            self._sleep(min(0.05, self.drain_deadline_s))
        return self.router.replica_inflight(index) <= 0

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Start the control thread (idempotent)."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle_tpu-autoscaler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._closed:
            self.tick()
            if self._degraded:
                return    # fixed fleet from here on; router lives
            self._wake.wait(self.poll_s)
            self._wake.clear()

    def close(self):
        self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s
                              + self.drain_deadline_s + 2.0)

    # -- observability -------------------------------------------------------
    @property
    def degraded(self):
        return self._degraded

    @property
    def breaker_state(self):
        return self._breaker

    def stats(self):
        """The ``autoscale`` section of RouterStats ``/statz``. Called
        cross-thread (the /statz HTTP handlers through Router.stats);
        everything the control thread mutates is snapshotted under the
        state lock."""
        with self._lock:
            counts = dict(self._counts)
            decisions = list(self._decisions[-8:])
            warming = sorted(self._pending)
            up_streak = self._up_streak
            quiet_streak = self._quiet_streak
            breaker = self._breaker
            last_signal = self._last_signal
            degraded = self._degraded
            degraded_error = self._degraded_error
        out = {
            "active": self._safe_active(),
            "tier": self.tier,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "pressure": last_signal,
            "up_pressure": self.up_pressure,
            "down_pressure": self.down_pressure,
            "k_up": self.k_up,
            "quiet_polls": self.quiet_polls,
            "up_streak": up_streak,
            "quiet_streak": quiet_streak,
            "warming": warming,
            "breaker": breaker,
            "degraded": degraded,
            "ups": counts.get("autoscale_ups", 0),
            "downs": counts.get("autoscale_downs", 0),
            "breaker_opens": counts.get("autoscale_breaker_opens", 0),
            "breaker_refused": counts.get("autoscale_breaker_refused",
                                          0),
            "last_decisions": decisions,
        }
        if degraded_error:
            out["degraded_error"] = degraded_error
        return out
