"""Stdlib JSON/HTTP front end over :class:`InferenceService`.

Deliberately ``http.server``-based: the container constraint is "no new
dependencies", and a serving tier whose transport is three stdlib
classes is also trivially auditable. The routes follow the de-facto
model-server shape (one verb-suffixed model URL, health and stats
endpoints):

====== ================================ ===================================
method path                             body / response
====== ================================ ===================================
POST   ``/v1/models/<name>:predict``    ``{"inputs": {feed: nested-list},
                                        "deadline_ms": optional}`` ->
                                        ``{"outputs": [...], "model":
                                        name, "version": v}``
POST   ``/v1/models/<name>:generate``   ``{"tokens": [ids],
                                        "max_new_tokens": N,
                                        "temperature": t, "seed": s,
                                        "deadline_ms": optional}`` ->
                                        ``{"tokens": [...],
                                        "finish_reason": ...,
                                        "ttft_ms": ..., ...}``
POST   ``/v1/models/<name>:prefill``    ``{"tokens": [ids],
                                        "max_new_tokens": N,
                                        "temperature": t, "seed": s}`` ->
                                        handoff-artifact wire payload
                                        (prefill-tier half of the
                                        disaggregated hop)
POST   ``/v1/models/<name>:decode``     ``{"artifact": payload,
                                        "deadline_ms": optional}`` ->
                                        GenResult fields (decode-tier
                                        half; a bad artifact
                                        re-prefills here — the
                                        ``serving.ship`` fallback)
POST   ``/v1/models/<name>:reload``     ``{"dirname": path}`` -> new
                                        version, or 409 + rollback info
GET    ``/v1/models``                   registry listing (both kinds)
GET    ``/healthz``                     liveness + registered models
GET    ``/statz``                       ``InferenceService.stats``
====== ================================ ===================================

Error mapping: 429 overload shed (and kv-pool exhaustion — kind
``kv_pool_exhausted``: backpressure, not a server fault), 504 deadline
shed, 404 unknown model, 400 malformed input, 500 dispatch failure —
each body carries ``{"error": ..., "kind": ...}``. 429 answers also
carry a back-off hint derived from current queue-wait stats
(``InferenceService.retry_after_ms``): a ``Retry-After`` header in
integral delta-seconds plus the precise ``retry_after_ms`` body field,
so clients (and the router) back off proportionally to the actual
backlog. ``/healthz`` keeps its 200-liveness contract and adds a
``ready`` object — per-model kind/version/queue depth, and for
generative models KV page utilization + draining state — the readiness
detail the router tier weights and drains on. The server is a
``ThreadingHTTPServer``: one thread per connection *blocks* in
``InferenceService.infer``/``generate`` while a single dispatch/engine
thread batches across them — concurrency lives in the batcher and the
generation engine, not here.
"""
from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .admission import (DeadlineExceededError, ModelUnavailableError,
                        OverloadError)

__all__ = ["make_server", "serve_until_shutdown"]

_MAX_BODY = 64 * 1024 * 1024


def write_json_reply(handler, code, payload, retry_after_ms=None):
    """Serialize one JSON answer on ``handler`` (the serve AND router
    handlers share this — the Retry-After contract must not drift).
    ``retry_after_ms`` (429/503 answers) adds both faces of the
    back-off hint: a ``Retry-After`` header in RFC 7231 integral
    delta-seconds (ceil, min 1) for generic clients, and the precise
    ``retry_after_ms`` in the body for the router and our own clients —
    derived from current queue-wait stats so backoff scales with the
    actual backlog instead of a fixed constant."""
    if retry_after_ms is not None:
        payload = dict(payload)
        payload["retry_after_ms"] = round(float(retry_after_ms), 3)
    body = json.dumps(payload).encode("utf-8")
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    if retry_after_ms is not None:
        handler.send_header("Retry-After",
                            str(max(1, int(-(-retry_after_ms // 1000)))))
    handler.end_headers()
    handler.wfile.write(body)


def read_json_body(handler):
    """Read + parse one request's JSON object body on ``handler`` (the
    serve AND router handlers share this — the size cap and dict
    contract must not drift). Raises ValueError on an oversized or
    non-object body; the caller maps it to a 400."""
    n = int(handler.headers.get("Content-Length") or 0)
    if n > _MAX_BODY:
        raise ValueError("request body too large (%d bytes)" % n)
    raw = handler.rfile.read(n) if n else b"{}"
    body = json.loads(raw.decode("utf-8"))
    if not isinstance(body, dict):
        raise ValueError("request body must be a JSON object")
    return body


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # request logging would serialize every request on stderr writes
    server_version = "paddle_tpu-serve"

    def log_message(self, fmt, *args):
        pass

    @property
    def service(self):
        return self.server.service

    # -- plumbing ------------------------------------------------------------
    def _reply(self, code, payload, retry_after_ms=None):
        write_json_reply(self, code, payload,
                         retry_after_ms=retry_after_ms)

    def _retry_hint(self, model=None):
        try:
            return self.service.retry_after_ms(model)
        except Exception:           # the hint must never fail the shed
            return 1000.0

    def _read_json(self):
        return read_json_body(self)

    # -- routes --------------------------------------------------------------
    def do_GET(self):
        if self.path == "/healthz":
            # liveness contract unchanged (200 + "ok" + "models" —
            # existing callers keep working); "ready" adds the per-model
            # readiness detail the router weights and drains on
            self._reply(200, {"ok": True,
                              "tier": getattr(self.service, "tier", ""),
                              "models": self.service.model_info(),
                              "ready": self.service.readiness()})
        elif self.path == "/statz":
            self._reply(200, self.service.stats)
        elif self.path == "/v1/models":
            self._reply(200, self.service.model_info())
        else:
            self._reply(404, {"error": "no route %r" % self.path,
                              "kind": "not_found"})

    def do_POST(self):
        try:
            body = self._read_json()
        except Exception as e:
            # the body may be partly or wholly unread (oversized guard):
            # replying on a keep-alive connection would desync it — the
            # leftover bytes would parse as the next request line
            self.close_connection = True
            return self._reply(400, {"error": "bad JSON body: %s" % e,
                                     "kind": "bad_request"})
        if self.path.startswith("/v1/models/") and \
                self.path.endswith(":predict"):
            name = self.path[len("/v1/models/"):-len(":predict")]
            return self._predict(name, body)
        if self.path.startswith("/v1/models/") and \
                self.path.endswith(":generate"):
            name = self.path[len("/v1/models/"):-len(":generate")]
            return self._generate(name, body)
        if self.path.startswith("/v1/models/") and \
                self.path.endswith(":prefill"):
            name = self.path[len("/v1/models/"):-len(":prefill")]
            return self._prefill(name, body)
        if self.path.startswith("/v1/models/") and \
                self.path.endswith(":decode"):
            name = self.path[len("/v1/models/"):-len(":decode")]
            return self._decode(name, body)
        if self.path.startswith("/v1/models/") and \
                self.path.endswith(":reload"):
            name = self.path[len("/v1/models/"):-len(":reload")]
            return self._reload(name, body)
        self._reply(404, {"error": "no route %r" % self.path,
                          "kind": "not_found"})

    def _predict(self, name, body):
        try:
            entry = self.service.registry.get(name)
            inputs = body.get("inputs")
            if not isinstance(inputs, dict):
                raise ValueError('body must carry {"inputs": {name: '
                                 "nested-list}}")
            # only CONVERT here (JSON nested lists -> exported dtype);
            # the signature itself — missing names, shapes — is checked
            # once, by the service's _checked_feed, whose ValueError
            # maps to 400 below
            spec = entry.model.feed_spec
            feed = {fn: np.asarray(inputs[fn], dtype=dtype)
                    for fn, (_, dtype) in spec.items() if fn in inputs}
            rows = self.service.infer(name, feed,
                                      deadline_ms=body.get("deadline_ms"))
        except ModelUnavailableError as e:
            return self._reply(404, {"error": str(e),
                                     "kind": "model_unavailable"})
        except OverloadError as e:
            return self._reply(429, {"error": str(e), "kind": "overload"},
                               retry_after_ms=self._retry_hint(name))
        except DeadlineExceededError as e:
            return self._reply(504, {"error": str(e), "kind": "deadline"})
        except ValueError as e:
            return self._reply(400, {"error": str(e),
                                     "kind": "bad_request"})
        except Exception as e:
            return self._reply(500, {"error": repr(e), "kind": "dispatch"})
        # report from the entry captured at admission: re-fetching here
        # would race a concurrent unload/reload into a lost response or
        # a version that never served this request
        self._reply(200, {
            "model": name, "version": entry.version,
            "fetch_names": list(entry.model.fetch_names),
            "outputs": [np.asarray(r).tolist() for r in rows]})

    def _generate(self, name, body):
        """Autoregressive generation: ``{"tokens": [ids],
        "max_new_tokens": N, "temperature": t, "seed": s,
        "deadline_ms": optional, "spec_k": optional}`` -> the GenResult
        fields. ``spec_k`` caps this request's speculation depth on a
        speculative engine (0 = plain decode); ignored elsewhere. Pool
        exhaustion is backpressure, not a server fault: 429 with kind
        ``kv_pool_exhausted``."""
        from .kvcache import PoolExhausted
        try:
            tokens = body.get("tokens")
            if not isinstance(tokens, list) or not tokens:
                raise ValueError('body must carry {"tokens": '
                                 "[token ids]}")
            spec_k = body.get("spec_k")
            # the handle carries the version of the engine that took the
            # submit — a re-fetch here would race a hot :reload into
            # attributing new-model tokens to the old version
            req = self.service.generate_async(
                name, tokens,
                max_new_tokens=int(body.get("max_new_tokens", 16)),
                temperature=float(body.get("temperature", 0.0)),
                seed=int(body.get("seed", 0)),
                deadline_ms=body.get("deadline_ms"),
                spec_k=None if spec_k is None else int(spec_k))
            res = req.wait()
        except ModelUnavailableError as e:
            return self._reply(404, {"error": str(e),
                                     "kind": "model_unavailable"})
        except PoolExhausted as e:
            return self._reply(429, {"error": str(e),
                                     "kind": "kv_pool_exhausted"},
                               retry_after_ms=self._retry_hint(name))
        except OverloadError as e:
            return self._reply(429, {"error": str(e), "kind": "overload"},
                               retry_after_ms=self._retry_hint(name))
        except DeadlineExceededError as e:
            return self._reply(504, {"error": str(e), "kind": "deadline"})
        except (TypeError, ValueError) as e:
            return self._reply(400, {"error": str(e),
                                     "kind": "bad_request"})
        except Exception as e:
            return self._reply(500, {"error": repr(e), "kind": "dispatch"})
        out = {"model": name, "version": req.model_version}
        out.update(res.describe())
        self._reply(200, out)

    def _prefill(self, name, body):
        """Prefill-tier half of the disaggregated hop: run ONLY the
        prompt pass and answer with the handoff artifact's wire payload
        (base64 KV pages + request state) for the router to ship to a
        decode-class replica. Same error mapping as :generate — the
        prefill pool exhausting on an over-long prompt is backpressure
        too."""
        from .kvcache import PoolExhausted
        try:
            tokens = body.get("tokens")
            if not isinstance(tokens, list) or not tokens:
                raise ValueError('body must carry {"tokens": '
                                 "[token ids]}")
            art = self.service.prefill(
                name, tokens,
                max_new_tokens=int(body.get("max_new_tokens", 16)),
                temperature=float(body.get("temperature", 0.0)),
                seed=int(body.get("seed", 0)))
        except ModelUnavailableError as e:
            return self._reply(404, {"error": str(e),
                                     "kind": "model_unavailable"})
        except PoolExhausted as e:
            return self._reply(429, {"error": str(e),
                                     "kind": "kv_pool_exhausted"},
                               retry_after_ms=self._retry_hint(name))
        except OverloadError as e:
            return self._reply(429, {"error": str(e), "kind": "overload"},
                               retry_after_ms=self._retry_hint(name))
        except (TypeError, ValueError) as e:
            return self._reply(400, {"error": str(e),
                                     "kind": "bad_request"})
        except Exception as e:
            return self._reply(500, {"error": repr(e), "kind": "dispatch"})
        self._reply(200, {"model": name, "artifact": art.to_payload()})

    def _decode(self, name, body):
        """Decode-tier half: install a shipped artifact into ``name``'s
        engine and decode to completion. A malformed artifact is the
        SENDER's fault (400); an install failure re-prefills here via
        the ``serving.ship`` fallback and still answers 200 — slower,
        never lost."""
        from .kvcache import PoolExhausted
        try:
            payload = body.get("artifact")
            if not isinstance(payload, dict):
                raise ValueError('body must carry {"artifact": '
                                 "handoff payload}")
            req = self.service.decode_handoff_async(
                name, payload, deadline_ms=body.get("deadline_ms"))
            res = req.wait()
        except ModelUnavailableError as e:
            return self._reply(404, {"error": str(e),
                                     "kind": "model_unavailable"})
        except PoolExhausted as e:
            return self._reply(429, {"error": str(e),
                                     "kind": "kv_pool_exhausted"},
                               retry_after_ms=self._retry_hint(name))
        except OverloadError as e:
            return self._reply(429, {"error": str(e), "kind": "overload"},
                               retry_after_ms=self._retry_hint(name))
        except DeadlineExceededError as e:
            return self._reply(504, {"error": str(e), "kind": "deadline"})
        except (TypeError, ValueError) as e:
            return self._reply(400, {"error": str(e),
                                     "kind": "bad_request"})
        except Exception as e:
            return self._reply(500, {"error": repr(e), "kind": "dispatch"})
        out = {"model": name, "version": req.model_version}
        out.update(res.describe())
        self._reply(200, out)

    def _reload(self, name, body):
        dirname = body.get("dirname")
        if not dirname:
            return self._reply(400, {"error": 'reload wants {"dirname": '
                                              "path}",
                                     "kind": "bad_request"})
        try:
            entry = self.service.reload_model(name, dirname)
        except Exception as e:
            # rollback: the previously published version keeps serving
            kept = None
            try:
                kept = self.service.registry.get(name).version
            except ModelUnavailableError:
                pass
            return self._reply(409, {"error": repr(e), "kind": "reload",
                                     "serving_version": kept})
        self._reply(200, {"model": name, "version": entry.version,
                          "warmup_ms": entry.warmup_ms})


def make_server(service, host="127.0.0.1", port=0):
    """Bind a :class:`ThreadingHTTPServer` over ``service``; ``port=0``
    picks a free port (read it back from ``server.server_address``).
    The caller owns ``serve_forever()`` / ``shutdown()``."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.service = service
    return server


def serve_until_shutdown(server, signals=None):
    """``serve_forever`` with clean signal-driven shutdown. ``signals``
    (default SIGTERM+SIGINT) trip ``server.shutdown()`` from a helper
    thread — calling it from the handler's own (main) thread would
    deadlock against the blocked ``serve_forever``. Returns the signal
    number that stopped the server, or None after an external
    ``shutdown()``. Restores previous handlers."""
    import signal as _signal
    import threading
    signals = signals if signals is not None else (_signal.SIGTERM,
                                                   _signal.SIGINT)
    stopped = {"signum": None}
    previous = {}

    def on_signal(signum, frame):
        stopped["signum"] = signum
        threading.Thread(target=server.shutdown, daemon=True).start()

    for s in signals:
        previous[s] = _signal.signal(s, on_signal)
    try:
        server.serve_forever(poll_interval=0.1)
    finally:
        for s, h in previous.items():
            _signal.signal(s, h)
    return stopped["signum"]
