"""Copy-on-write prefix sharing over the paged KV pool.

Millions of requests open with the same system prompt, yet the paged
engine (serving/generator.py) pays full-price KV pages for every one of
them. This module is the dedup layer: after a prefill writes a prompt
into its pages, each page's *content* — the exact token chunk it holds,
chained to everything before it — is hashed and published here; the
next request whose prompt starts with the same chunks PINS those same
physical pages into its own BlockTable (``PagePool.ref``) instead of
allocating and recomputing… the pool's refcounts make N concurrent
tables share one physical prefix safely.

**The chain key.** Page *i* of a prompt covers token chunk
``[i*T, min(L, (i+1)*T))`` (T = ``serve_page_tokens``). Its key is
``blake2b(key_{i-1} || chunk_bytes)`` — a rolling hash, so a chunk only
matches at the same position after the same history, and a partial
final chunk (different byte length) can never collide with a full one.
Content-addressing is sound because K/V at a position is a
deterministic function of the token prefix alone: same tokens, same
compiled prefill, bit-identical page bytes.

**Copy-on-write is the ENGINE's move, not ours.** Shared pages are
immutable history; the first *divergent* write (a generated token
landing inside a shared page — only possible for a partial final
chunk) makes the engine allocate a fresh page, device-copy that one
page, and swap it into the table (``GenerationEngine._unshare_for_
write``). Prefill re-scatters over matched pages are bit-identical
rewrites and need no copy.

**LRU warmth.** The cache holds its OWN reference on every published
page, so a prompt stays warm after its last user retires
(unreferenced-but-cached). Under allocation pressure the pool's
reclaimer hook (``PagePool.set_reclaimer``) walks this LRU oldest-first
and evicts entries whose page only the cache still pins — cold prefix
pages yield to live traffic before exhaustion ever fires, and entries
still shared with running tables are never force-freed.

Fault site ``serving.prefix`` (hit at cache build and per match):
a raise degrades that engine to plain no-sharing private pages for its
lifetime with a recorded ``prefix_degraded`` event — a memory-economics
regression, never an outage, and greedy output is bit-identical with
sharing on or off.
"""
from __future__ import annotations

import collections
import hashlib

from ..resilience import fault_point
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["PrefixCache", "chunk_keys"]


def chunk_keys(tokens, page_tokens):
    """Yield ``(key, start, end)`` per page-sized chunk of ``tokens``
    (the final chunk may be partial). ``key`` is the 16-byte rolling
    blake2b chain digest — position- and history-dependent."""
    tokens = list(tokens)
    T = int(page_tokens)
    prev = b""
    for start in range(0, len(tokens), T):
        chunk = tokens[start:start + T]
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(b",".join(b"%d" % int(t) for t in chunk))
        prev = h.digest()
        yield prev, start, start + len(chunk)


class _Entry(object):
    __slots__ = ("key", "page", "tokens")

    def __init__(self, key, page, tokens):
        self.key = key
        self.page = page       # physical page id (cache holds one ref)
        self.tokens = tokens   # positions of the page actually covered


class PrefixCache(object):
    """Content-addressed prefix-page cache over ONE :class:`PagePool`.

    Thread-safe; lock order is cache -> pool (``match``/``publish``/
    ``_reclaim`` take the cache lock then call into the pool), and the
    pool calls the reclaimer OUTSIDE its own lock, so the order can
    never invert.
    """

    def __init__(self, pool, name="model"):
        fault_point("serving.prefix")
        self.pool = pool
        self.name = name
        self._lock = _locks.make_lock("serving.prefix.cache")
        # key -> _Entry, in LRU order (oldest first)
        self._entries = collections.OrderedDict()
        self._counts = collections.Counter()
        pool.set_reclaimer(self._reclaim)

    # -- lookup ---------------------------------------------------------------
    def probe(self, tokens):
        """How many leading FULL pages of ``tokens`` are cached right
        now — the admission discount: these pages will be pinned, not
        allocated, so the reservation shrinks by this many. Partial
        final chunks are deliberately NOT counted even when cached: the
        first generated token lands inside that page and copy-on-write
        buys it back, so discounting it would let admission overdraw
        the pool by one page per request. No pinning, no LRU touch —
        a feasibility probe, racing eviction is handled by the
        admission requeue path."""
        T = self.pool.page_tokens
        n = 0
        with self._lock:
            for key, start, end in chunk_keys(tokens, T):
                if end - start < T or key not in self._entries:
                    break
                n += 1
        return n

    def match(self, tokens):
        """Pin the longest cached page run covering a prefix of
        ``tokens``: each matched page gets one ``pool.ref`` for the
        caller's BlockTable (released through the table's normal
        ``free`` path). Returns ``(pages, covered_tokens)``. Matched
        entries move to MRU."""
        fault_point("serving.prefix")
        pages, covered = [], 0
        with self._lock:
            for key, start, end in chunk_keys(tokens, self.pool.page_tokens):
                entry = self._entries.get(key)
                if entry is None or entry.tokens != end - start:
                    break
                self._entries.move_to_end(key)
                pages.append(entry.page)
                covered = end
            if pages:
                self.pool.ref(pages)
                self._counts["hits"] += len(pages)
                self._counts["hit_requests"] += 1
            else:
                self._counts["miss_requests"] += 1
        return pages, covered

    # -- publish --------------------------------------------------------------
    def publish(self, tokens, pages):
        """Register the pages now holding ``tokens`` (page *i* of
        ``pages`` holds chunk *i*; the final chunk may be partial —
        partial pages ARE published, that is what makes same-prompt
        requests share their tail page until copy-on-write diverges
        them). Already-cached chunks are skipped (and refreshed to
        MRU); new entries pin one cache reference per page. Returns the
        number of pages newly published."""
        published = 0
        with self._lock:
            for i, (key, start, end) in enumerate(
                    chunk_keys(tokens, self.pool.page_tokens)):
                if i >= len(pages):
                    break
                if key in self._entries:
                    self._entries.move_to_end(key)
                    continue
                self.pool.ref([pages[i]])
                self._entries[key] = _Entry(key, pages[i], end - start)
                published += 1
            self._counts["published"] += published
        return published

    # -- eviction -------------------------------------------------------------
    def _reclaim(self, n_short):
        """PagePool pressure hook: evict cold entries — oldest first,
        only those whose page the cache alone still pins (refcount 1;
        freeing those actually returns pages) — until ``n_short`` pages
        came back or the LRU runs dry. Returns pages freed."""
        freed = 0
        with self._lock:
            for key in list(self._entries):
                if freed >= n_short:
                    break
                entry = self._entries[key]
                if self.pool.refcount(entry.page) != 1:
                    continue   # a running table still shares it
                del self._entries[key]
                self.pool.free([entry.page])
                freed += 1
            self._counts["evictions"] += freed
        return freed

    def reset(self):
        """Drop every entry and its cache reference but stay
        registered — the pool-rebuild path (``_ensure_pools``): the
        arrays were re-zeroed, so cached content is gone and serving a
        stale entry would splice zero pages into someone's prompt."""
        with self._lock:
            for entry in self._entries.values():
                try:
                    self.pool.free([entry.page])
                except ValueError:
                    pass   # pool accounting was reset under us
            self._entries.clear()

    def clear(self):
        """Full teardown (engine close / degrade): :meth:`reset` plus
        unregister from the pool's pressure hook."""
        self.reset()
        self.pool.set_reclaimer(None)

    # -- accounting -----------------------------------------------------------
    def stats(self):
        with self._lock:
            c = dict(self._counts)
            return {"entries": len(self._entries),
                    "hits": c.get("hits", 0),
                    "hit_requests": c.get("hit_requests", 0),
                    "miss_requests": c.get("miss_requests", 0),
                    "published": c.get("published", 0),
                    "evictions": c.get("evictions", 0)}
