"""Continuous (iteration-level) batching: the autoregressive engine.

The PR-4 micro-batcher is *request*-level: it stacks whole requests and
returns when the whole stack returns — correct for one-shot inference,
wrong by construction for autoregressive decode, where a batch would run
at the pace of its longest sequence and every finished row would keep
burning device time as padding. This engine schedules at the *iteration*
level (Orca; vLLM's continuous batching, PAPERS.md): one loop that each
step

1. **admits** queued prompts into free slots while their page
   reservation fits (token-budget admission over the paged KV pool),
   running one prefill per admitted prompt (traced once per
   prompt-length bucket),
2. runs **ONE fused decode step** for every running sequence at once —
   sequences at arbitrary, different positions — via
   ``models/transformer.decode_step``; attention reads K/V through the
   block tables (the Pallas paged-attention kernel when a
   paddle_tpu.tune winner picked one, the gather otherwise) and the
   step's operand shapes are fixed by (max_running, pool shape), so it
   is compiled ONCE and the hot loop is trace-free at any mix of
   sequence lengths,
3. **samples** (greedy or seeded temperature categorical) — ON DEVICE
   inside the same jit by default (``FLAGS.serve_device_sample``): the
   step returns ``[R]`` int32 tokens plus the per-row logprob instead
   of ``[R, V]`` logits, the host loop is pure bookkeeping, and
   ``gen_host_logit_syncs`` stays 0. With the flag off, sampling runs
   on host from the returned logits — bit-identical to the pre-fused
   engine — and host sampling is also the automatic fallback when the
   fused build fails (fault site ``serving.sample``, recorded
   ``device_sample_degraded`` event, the engine keeps serving), and
4. **retires** finished sequences immediately — their slot and pages
   recycle into the next step's admission, mid-flight.

Degrade-and-record, never crash: pool exhaustion at submit is a shed
with a recorded ``kv_pool_exhausted`` event; mid-flight starvation (only
possible under ``reserve="prompt"``) preempts the starved sequence back
to the queue head (recompute-on-resume — greedy decode re-derives the
same continuation, and a resumed request's device RNG stream continues
at its sequence position) or sheds it when preemption cannot help; a raise at
fault site ``serving.generate`` fails that step's sequences with a
``generate_failed`` event and the loop keeps serving.

**Speculative decoding** (``draft_model=`` + ``spec_k``, flags
``serve_draft_dir``/``serve_spec_k``): each decode step becomes a
draft-propose / fused-verify round — a small draft model proposes up to
k tokens into ITS OWN page pool (``serving/speculative.DraftEngine``),
then ONE k+1-lane fused target step verifies them all, accepting the
longest valid prefix and sampling the correction on device
(``models/transformer.verify_step_sampled``). Greedy output is
token-identical to plain decode; tempered rows use rejection sampling on
the position-keyed RNG stream so preemption replays exactly. Rejected
lanes cost only a page-table trim (``BlockTable.trim``) — never a cache
rollback. Fault site ``serving.speculate`` degrades speculation to plain
fused decode with a ``speculation_degraded`` event.

**Prefix sharing** (``prefix_sharing=`` / ``FLAGS.serve_prefix_sharing``,
``serving/prefix.py``): prefill pages are content-hashed and published
to a per-engine cache; a later request whose prompt starts with the
same chunks PINS the same physical pages (``PagePool.ref``) instead of
allocating them — admission reserves against *effective* (dedup-aware)
pages while exhaustion stays priced in *physical* pages, so N
same-prefix requests admit past the pool's nominal private capacity.
The first divergent write into a still-shared page (a generated token
landing in a shared partial tail page) triggers copy-on-write: ONE
page is allocated and device-copied, the table swaps to it, the shared
original stays pristine for everyone else. Greedy output is
bit-identical with sharing on or off (same tokens ⇒ same page bytes ⇒
same attention reads). Fault site ``serving.prefix`` degrades the
engine to plain private pages with a recorded ``prefix_degraded``
event — a memory regression, never an outage.

**Disaggregated decode** (``serving/disagg.py``): ``submit_prefilled``
accepts a handoff artifact — finished KV page contents + request state
exported by a prefill-tier engine — and INSTALLS the pages into this
engine's pool instead of recomputing the prefill; the request then
decodes here as if it had prefilled locally (same position-keyed RNG
stream, bit-identical continuation). A failed handoff re-prefills on
this tier through the normal ``submit`` path (fault site
``serving.ship``, recorded ``handoff_failed`` — slower, never lost).

Knobs: ``FLAGS.serve_max_running`` / ``serve_kv_pages`` /
``serve_page_tokens`` / ``serve_queue_depth`` /
``serve_device_sample`` / ``serve_prefix_sharing``. Metrics mirror
into ``profiler.generation_counters()`` and the timeline artifact's
``generation`` + ``prefix`` sections.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

from ..resilience import fault_point, record_event
from .admission import (AdmissionController, DeadlineExceededError,
                        OverloadError, ServingError)
from .batcher import bucket_for, padding_buckets
from .kvcache import BlockTable, PagePool, PoolExhausted, pages_for
from .prefix import PrefixCache
from .service import _WINDOW, _percentile
from .speculative import DraftEngine
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["GenRequest", "GenResult", "GenerationEngine", "sample_token",
           "reference_decode"]

# how many preemptions one request may absorb before the engine calls
# the pool genuinely too small for it and sheds instead of thrashing
_PREEMPT_LIMIT = 2


def sample_token(logits, temperature, rng):
    """One token id from a [V] logits row — THE sampling rule, shared by
    the engine, the sequential reference, and the benchmarks so parity
    can never drift. ``temperature <= 0`` is greedy (np.argmax,
    deterministic tie-break); otherwise softmax at ``temperature``
    sampled with ``rng`` (np.random.RandomState)."""
    logits = np.asarray(logits, np.float64)
    if temperature is None or temperature <= 0.0:
        return int(np.argmax(logits))
    z = (logits - logits.max()) / float(temperature)
    p = np.exp(z)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


def reference_decode(model, prompt, max_new_tokens, temperature=0.0,
                     seed=0, eos_id=None):
    """Sequential full-sequence decode: recompute the whole forward per
    token, no cache — the slow, obviously-correct decoder the
    continuous-batching parity proof compares against (greedy outputs
    must be token-identical)."""
    import jax.numpy as jnp
    if eos_id is None:
        eos_id = model.config.eos_id
    toks = [int(t) for t in prompt]
    out = []
    rng = np.random.RandomState(seed)
    for _ in range(int(max_new_tokens)):
        logits = np.asarray(
            model.forward(jnp.asarray([toks], jnp.int32)))[0, -1]
        t = sample_token(logits, temperature, rng)
        out.append(t)
        toks.append(t)
        if eos_id is not None and t == eos_id:
            break
    return out


class GenResult(object):
    """What a finished generation resolves to. ``logprobs`` is the
    per-token log-softmax of the raw (untempered) logits at the chosen
    token — populated by the device-sampling fast path (it rides back
    with the token, so the retire path never re-materializes logits);
    ``None`` on the host-sampling path."""

    __slots__ = ("tokens", "finish_reason", "ttft_ms", "latency_ms",
                 "preemptions", "logprobs")

    def __init__(self, tokens, finish_reason, ttft_ms, latency_ms,
                 preemptions, logprobs=None):
        self.tokens = tokens
        self.finish_reason = finish_reason
        self.ttft_ms = ttft_ms
        self.latency_ms = latency_ms
        self.preemptions = preemptions
        self.logprobs = logprobs

    def describe(self):
        out = {"tokens": list(self.tokens),
               "finish_reason": self.finish_reason,
               "ttft_ms": round(self.ttft_ms, 3),
               "latency_ms": round(self.latency_ms, 3),
               "preemptions": self.preemptions}
        if self.logprobs is not None:
            out["logprobs"] = [round(lp, 6) for lp in self.logprobs]
        return out


class GenRequest(object):
    """One queued/running generation; resolves to a :class:`GenResult`.

    Sampled tokens accumulate HERE (not on the running slot), so a
    preempted request carries its progress back through the queue and
    resumes by prefilling prompt+progress — no token is ever re-sampled,
    and its RNG stream continues where it stopped."""

    __slots__ = ("prompt", "max_new_tokens", "temperature", "seed",
                 "deadline_t", "enqueue_t", "tokens", "logprobs",
                 "preemptions", "model_version", "spec_k", "handoff",
                 "_rng", "_ttft_ms", "_done", "_result", "_error")

    def __init__(self, prompt, max_new_tokens, temperature=0.0, seed=0,
                 deadline_t=None, spec_k=None):
        self.prompt = [int(t) for t in prompt]
        # per-request speculation-depth cap (None = engine default;
        # 0 = plain decode for this request). Part of the request
        # IDENTITY: a resumed preemption re-derives the same round
        # boundaries from it, which the tempered replay proof needs.
        self.spec_k = None if spec_k is None else int(spec_k)
        # stamped by InferenceService.generate_async: the registry
        # version of the engine that took this submit
        self.model_version = None
        # a disaggregated handoff artifact (serving/disagg.py): the
        # FIRST _start installs its exported pages instead of
        # prefilling, then clears this — a later preemption resumes
        # through the normal recompute-on-resume prefill
        self.handoff = None
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature or 0.0)
        self.seed = int(seed or 0)
        self.deadline_t = deadline_t
        self.enqueue_t = time.monotonic()
        self.tokens = []
        # device-sampling path only: one logprob per sampled token
        # (carried with tokens, so preemption keeps them aligned)
        self.logprobs = []
        self.preemptions = 0
        self._rng = np.random.RandomState(self.seed)
        self._ttft_ms = None
        self._done = threading.Event()
        self._result = None
        self._error = None

    @property
    def budget_left(self):
        return self.max_new_tokens - len(self.tokens)

    @property
    def pending_prompt(self):
        """What a (re)prefill must feed: original prompt + progress."""
        return self.prompt + self.tokens

    def resolve(self, finish_reason):
        self._result = GenResult(
            list(self.tokens), finish_reason,
            self._ttft_ms if self._ttft_ms is not None else 0.0,
            (time.monotonic() - self.enqueue_t) * 1e3, self.preemptions,
            logprobs=(list(self.logprobs)
                      if len(self.logprobs) == len(self.tokens)
                      else None))
        self._done.set()

    def fail(self, exc):
        self._error = exc
        self._done.set()

    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block for the :class:`GenResult`; re-raises shed/step errors."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still pending after %.3fs"
                               % (timeout,))
        if self._error is not None:
            raise self._error
        return self._result


class _Running(object):
    """One occupied engine slot."""

    __slots__ = ("req", "slot", "table", "cached", "last_token", "last_t",
                 "spec_cap")

    def __init__(self, req, slot, table):
        self.req = req
        self.slot = slot
        self.table = table
        self.cached = 0          # positions written into the paged cache
        self.last_token = None   # next decode step's input token
        self.last_t = time.monotonic()
        self.spec_cap = 0        # draft lanes this row runs this round


class GenerationEngine(object):
    """The per-model generation engine: paged KV pool + one engine
    thread running the admit/decode/sample/retire loop.

    ``reserve`` — the token-budget admission policy:

    - ``"full"`` (default): admission reserves pages for
      prompt + max_new_tokens, so a running sequence can never starve
      mid-flight; occupancy is bounded by worst-case reservations.
    - ``"prompt"``: admission reserves the prompt only and pages are
      allocated on demand at block boundaries; higher admission
      throughput, and mid-flight starvation is handled by preemption
      (recompute-on-resume) with a recorded ``kv_pool_exhausted`` event.

    ``device_sample`` — sample inside the jitted step (None defers to
    ``FLAGS.serve_device_sample``); a fused-face build failure degrades
    to host sampling with a recorded event (fault site
    ``serving.sample``). ``attn_config`` — a paddle_tpu.tune
    "paged_attention" pick for the decode step's attention; None
    consults the winner cache (miss/stock winner -> the gather path).
    Both are resolved ONCE here: the compiled-once decode contract
    means they cannot change on a live engine.
    """

    def __init__(self, model, max_running=None, kv_pages=None,
                 page_tokens=None, queue_depth=None, reserve="full",
                 eos_id=None, name="model", warm=False,
                 device_sample=None, attn_config=None, draft_model=None,
                 spec_k=None, prefix_sharing=None):
        import jax
        from ..flags import FLAGS
        if reserve not in ("full", "prompt"):
            raise ValueError("reserve must be 'full' or 'prompt'")
        self.model = model
        self.name = name
        self.reserve = reserve
        self.max_running = int(max_running if max_running is not None
                               else FLAGS.serve_max_running)
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else FLAGS.serve_queue_depth)
        page_tokens = int(page_tokens if page_tokens is not None
                          else FLAGS.serve_page_tokens)
        kv_pages = int(kv_pages if kv_pages is not None
                       else FLAGS.serve_kv_pages)
        cfg = model.config
        self.eos_id = cfg.eos_id if eos_id is None else int(eos_id)
        self.max_context = int(cfg.max_seq)
        self.max_blocks = pages_for(self.max_context, page_tokens)
        L, nh, dh = model.kv_spec
        self.pool = PagePool(kv_pages, page_tokens, L, nh, dh)
        self._kp, self._vp = self.pool.zeros()
        self._check_pool_install("serving.engine_pool_install")
        # copy-on-write prefix sharing: a per-engine content-addressed
        # cache over THIS pool; build failure (fault site
        # serving.prefix) degrades to plain private pages — recorded,
        # never an outage
        if prefix_sharing is None:
            prefix_sharing = bool(FLAGS.serve_prefix_sharing)
        self._prefix = None
        self._prefix_degraded = False
        if prefix_sharing:
            try:
                self._prefix = PrefixCache(self.pool, name=name)
            except BaseException as e:
                self._prefix_degraded = True
                record_event("prefix_degraded", site="serving.prefix",
                             model=name, phase="build", error=repr(e))
        # lazily-jitted page faces: _cow copies ONE page slice (the
        # copy-on-write move), _install scatters a handoff artifact's
        # exported pages into the pool; both donate so the pool is
        # updated in place, both compile once on first use
        self._cow = None
        self._install = None
        if attn_config is None:
            # one dispatch decision per engine: the decode step is
            # compiled ONCE, so the winner-cache consult happens here,
            # not per trace. A miss or a stock winner keeps the gather.
            from .. import tune as _tune
            from ..kernels.paged_attention import population_key
            attn_config = _tune.lookup(
                "paged_attention",
                population_key(self.max_running, self.max_blocks,
                               page_tokens, nh, dh), enabled=False)
        self.attn_config = attn_config or None
        # the two compiled faces: decode ONCE per (max_running, pool),
        # prefill once per prompt-length bucket; pools are donated so
        # the cache is updated in place step to step
        self._decode = jax.jit(model.decode_fn(self.attn_config),
                               donate_argnums=(1, 2))
        self._prefill = jax.jit(model.prefill_fn(), donate_argnums=(1, 2))
        # the fused (device-sampling) faces: same math + seeded
        # categorical in-jit; build failure degrades to host sampling
        # and the engine keeps serving (fault site serving.sample)
        if device_sample is None:
            device_sample = bool(FLAGS.serve_device_sample)
        self.device_sample = False
        self._decode_s = self._prefill_s = None
        self._sample_meta = None   # cached (temps, seeds) device copies
        if device_sample:
            try:
                fault_point("serving.sample")
                self._decode_s = jax.jit(
                    model.decode_sample_fn(self.attn_config),
                    donate_argnums=(1, 2))
                self._prefill_s = jax.jit(model.prefill_sample_fn(),
                                          donate_argnums=(1, 2))
                self.device_sample = True
            except BaseException as e:
                record_event("device_sample_degraded",
                             site="serving.sample", model=name,
                             error=repr(e))
        # prompt-length buckets share the batcher's padding policy (ONE
        # powers-of-two-capped algorithm for both tiers)
        self._buckets = padding_buckets(self.max_context)
        # speculative decoding: a DraftEngine (its own page pool +
        # propose face) plus the target's k+1-lane fused verify face.
        # Speculation REQUIRES the fused sampling faces — verification
        # IS device sampling — and any failure here (including an armed
        # serving.speculate fault) degrades to plain fused decode with
        # a recorded speculation_degraded event: a perf regression,
        # never an outage.
        if spec_k is None:
            spec_k = int(FLAGS.serve_spec_k)
        self.spec_k = int(spec_k) if draft_model is not None else 0
        self._spec = None
        self._spec_degraded = False
        self._verify_s = None
        if draft_model is not None and self.spec_k >= 1:
            try:
                if not self.device_sample:
                    raise ServingError(
                        "speculative decoding needs the fused "
                        "device-sampling faces, which did not build on "
                        "this engine")
                self._spec = DraftEngine(
                    draft_model, self.spec_k, cfg, kv_pages, page_tokens,
                    self.max_context, self._buckets, name=name)
                self._verify_s = jax.jit(
                    model.verify_sample_fn(self.attn_config),
                    donate_argnums=(1, 2))
            except BaseException as e:
                self._spec = None
                self._spec_degraded = True
                record_event("speculation_degraded",
                             site="serving.speculate", model=name,
                             phase="build", error=repr(e))
        self._queue = collections.deque()
        self._seqs = []            # _Running, slot-ordered
        self._admitting = 0        # popped from queue, prefill underway
        #   (in neither _queue nor _seqs — drain must count these too)
        self._free_slots = list(range(self.max_running))
        self._cond = _locks.make_condition("serving.generator.cond")
        self._alive = True
        self._draining = False
        self._counts = collections.Counter()
        self._busy_s = 0.0
        self._occupancy_sum = 0
        self._max_running_seen = 0
        self._page_util_max = 0.0
        self._ttft_ms = collections.deque(maxlen=_WINDOW)
        self._intertoken_ms = collections.deque(maxlen=_WINDOW)
        # warm BEFORE the engine thread exists — warm_up and the loop
        # share the donated pool arrays
        self.warmup_ms = self.warm_up() if warm else 0.0
        self._thread = threading.Thread(
            target=self._loop, name="paddle_tpu-generate-" + name,
            daemon=True)
        self._thread.start()

    def warm_up(self, buckets=None):
        """Pre-trigger every compile the request path can need — the
        fused decode step and each prompt bucket's prefill — with
        all-trash block tables, so the warm traffic writes only to the
        trash page and the live cache stays untouched. Returns the
        warm-up wall time in ms (the registry's load convention).
        Runs from the constructor (``warm=True``) before the engine
        thread starts; on a live engine it would race the loop's
        ownership of the donated pool arrays — don't."""
        import jax.numpy as jnp
        t0 = time.monotonic()
        trash_row = np.full((self.max_blocks,), self.pool.trash_page,
                            np.int32)
        for S_b in (self._buckets if buckets is None else buckets):
            if self.device_sample:
                _, _, self._kp, self._vp = self._prefill_s(
                    self.model.params, self._kp, self._vp,
                    jnp.asarray(np.zeros((S_b,), np.int32)), np.int32(1),
                    jnp.asarray(trash_row), np.float32(0.0), np.int32(0))
            else:
                _, self._kp, self._vp = self._prefill(
                    self.model.params, self._kp, self._vp,
                    jnp.asarray(np.zeros((S_b,), np.int32)), np.int32(1),
                    jnp.asarray(trash_row))
        R = self.max_running
        tables = jnp.asarray(np.tile(trash_row, (R, 1)))
        zeros_i = jnp.asarray(np.zeros((R,), np.int32))
        if self.device_sample:
            _, self._kp, self._vp = self._decode_s(
                self.model.params, self._kp, self._vp, tables, zeros_i,
                zeros_i, jnp.asarray(np.zeros((R,), bool)),
                jnp.asarray(np.zeros((R,), np.float32)), zeros_i)
        else:
            _, self._kp, self._vp = self._decode(
                self.model.params, self._kp, self._vp, tables, zeros_i,
                zeros_i, jnp.asarray(np.zeros((R,), bool)))
        if self._spec is not None:
            # one draft warm (prefill buckets + propose) whose device
            # outputs feed the verify warm — the speculative hot loop
            # is then trace-free too
            try:
                drafts, dlogits = self._spec.warm(R)
                _, self._kp, self._vp = self._verify_s(
                    self.model.params, self._kp, self._vp, tables,
                    zeros_i, zeros_i, drafts, dlogits,
                    jnp.asarray(np.zeros((R,), bool)),
                    jnp.asarray(np.zeros((R,), np.float32)), zeros_i,
                    zeros_i)
            except BaseException as e:
                self._degrade_spec("warm", e)
                self._ensure_pools()   # a verify raise may have
                #   consumed the donated target pool arrays
        return (time.monotonic() - t0) * 1e3

    # -- submit side ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, temperature=0.0, seed=0,
               deadline_ms=None, spec_k=None):
        """Queue one prompt; returns the :class:`GenRequest` handle.
        Sheds NOW (with the house recorded events) when the queue is
        full, the request could never fit the pool, or it exceeds the
        model's context window."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must hold at least one token id")
        V = self.model.config.vocab_size
        if min(prompt) < 0 or max(prompt) >= V:
            raise ValueError("prompt token ids must be in [0, %d)" % V)
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        temperature = float(temperature or 0.0)
        if not np.isfinite(temperature) or temperature < 0.0:
            # reject HERE, on the caller's thread: json accepts NaN, and
            # a NaN temperature reaching sample_token would raise on the
            # engine thread and fail every other in-flight generation
            raise ValueError("temperature must be finite and >= 0.0, "
                             "got %r" % temperature)
        if spec_k is not None:
            spec_k = int(spec_k)
            if spec_k < 0:
                raise ValueError("spec_k must be >= 0 (0 disables "
                                 "speculation for this request)")
        total = len(prompt) + max_new_tokens
        if total > self.max_context:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the model "
                "context window (%d)" % (len(prompt), max_new_tokens,
                                         self.max_context))
        req = GenRequest(prompt, max_new_tokens, temperature, seed,
                         AdmissionController.deadline_from(deadline_ms),
                         spec_k=spec_k)
        return self._enqueue(req)

    def _enqueue(self, req):
        """Shared submit tail for :meth:`submit` and
        :meth:`submit_prefilled`: the pool-feasibility shed (PHYSICAL
        pages — exhaustion policy never prices sharing in), the
        liveness/drain/queue-depth checks, and the actual append."""
        total = len(req.pending_prompt) + req.budget_left
        if not self.pool.can_fit(total):
            record_event("kv_pool_exhausted", site="serving.generate",
                         action="shed", model=self.name,
                         want_pages=pages_for(total,
                                              self.pool.page_tokens),
                         pool_pages=self.pool.num_pages)
            with self._cond:
                self._counts["shed_pool"] += 1
            self._update_prof(gen_shed_pool=1)
            raise PoolExhausted(
                "request needs %d token(s) of cache; the pool holds %d "
                "(serve_kv_pages=%d x serve_page_tokens=%d) — shed "
                "instead of wedging the engine"
                % (total, self.pool.num_pages * self.pool.page_tokens,
                   self.pool.num_pages, self.pool.page_tokens))
        with self._cond:
            if not self._alive:
                raise ServingError("generation engine is closed")
            if self._draining:
                raise ServingError(
                    "generation engine is draining (hot reload in "
                    "progress) — resubmit to the replacement engine")
            if len(self._queue) >= self.queue_depth:
                record_event("request_shed", site="serving.generate",
                             reason="overload", model=self.name,
                             queue_depth=self.queue_depth)
                self._counts["shed_overload"] += 1
                self._update_prof(gen_shed_overload=1)
                raise OverloadError(
                    "generation queue full (%d pending >= queue_depth="
                    "%d); request shed — retry with backoff or raise "
                    "FLAGS.serve_queue_depth"
                    % (len(self._queue), self.queue_depth))
            self._counts["submitted"] += 1
            self._queue.append(req)
            self._cond.notify_all()
        self._update_prof(gen_requests=1)
        return req

    def submit_prefilled(self, artifact, deadline_ms=None):
        """Queue a disaggregated handoff (serving/disagg.py): the
        artifact carries a prefill-tier engine's finished KV page
        contents plus the request state that makes the decode
        continuation bit-exact (first sampled token + logprob,
        temperature, seed — the position-keyed device RNG stream needs
        nothing else). ``_start`` INSTALLS the pages instead of
        recomputing the prefill. Speculation is disabled for handoff
        requests (``spec_k=0``): the draft pool never saw the prompt,
        and plain fused decode is bit-identical anyway. Sheds exactly
        like :meth:`submit` (queue depth, physical feasibility)."""
        pool = self.pool
        if (int(artifact.page_tokens) != pool.page_tokens
                or int(artifact.num_layers) != pool.num_layers
                or int(artifact.num_heads) != pool.num_heads
                or int(artifact.head_dim) != pool.head_dim):
            raise ServingError(
                "handoff artifact geometry (layers=%s heads=%s "
                "head_dim=%s page_tokens=%s) does not match this "
                "engine's pool (layers=%d heads=%d head_dim=%d "
                "page_tokens=%d) — the tiers must serve the same model "
                "geometry" % (artifact.num_layers, artifact.num_heads,
                              artifact.head_dim, artifact.page_tokens,
                              pool.num_layers, pool.num_heads,
                              pool.head_dim, pool.page_tokens))
        prompt = [int(t) for t in artifact.prompt]
        max_new_tokens = int(artifact.max_new_tokens)
        if len(prompt) + max_new_tokens > self.max_context:
            raise ValueError(
                "handoff prompt (%d) + max_new_tokens (%d) exceeds the "
                "model context window (%d)"
                % (len(prompt), max_new_tokens, self.max_context))
        req = GenRequest(prompt, max_new_tokens,
                         float(artifact.temperature),
                         int(artifact.seed),
                         AdmissionController.deadline_from(deadline_ms),
                         spec_k=0)
        req.tokens = [int(artifact.first_token)]
        if artifact.first_logprob is not None:
            req.logprobs = [float(artifact.first_logprob)]
        if (self.eos_id is not None and req.tokens[0] == self.eos_id) \
                or req.budget_left <= 0:
            # the prefill tier's one token already finished the request
            with self._cond:
                self._counts["submitted"] += 1
                self._counts["completed"] += 1
            self._update_prof(gen_requests=1, gen_completed=1)
            req._ttft_ms = 0.0
            req.resolve("eos" if req.tokens[0] == self.eos_id
                        else "length")
            return req
        req.handoff = artifact
        return self._enqueue(req)

    def generate(self, prompt, max_new_tokens=16, temperature=0.0, seed=0,
                 deadline_ms=None, timeout=None, spec_k=None):
        """Blocking convenience: submit + wait -> :class:`GenResult`."""
        return self.submit(prompt, max_new_tokens, temperature, seed,
                           deadline_ms, spec_k=spec_k).wait(timeout)

    # -- engine loop ---------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while self._alive and not self._queue and not self._seqs:
                    self._cond.wait(0.1)
                if not self._alive:
                    return
            try:
                self._admit()
                if self._seqs:
                    self._step()
                else:
                    # queued work that cannot admit yet (e.g. a requeue
                    # race on the pool): block briefly instead of
                    # spinning the admission check
                    with self._cond:
                        if self._alive and self._queue:
                            self._cond.wait(0.01)
            except BaseException as e:
                # engine-thread bugs degrade to failed requests, never a
                # silently dead loop (the batcher's contract)
                self._fail_running(e)

    @property
    def draining(self):
        """True between :meth:`drain` and :meth:`close` — the hot-reload
        handover window. Surfaces in the /healthz readiness detail so a
        router stops sending new work here."""
        with self._cond:
            return self._draining

    def drain(self, timeout=None):
        """Stop accepting new submits and wait for the queue and the
        running set to empty — the hot-reload handover: in-flight
        generations finish on THIS engine while the replacement takes
        new traffic. Returns True when fully drained, False on timeout
        (the caller decides whether to close anyway)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._draining = True
            while self._alive and (self._queue or self._seqs
                                   or self._admitting):
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                self._cond.wait(0.05)
            return not (self._queue or self._seqs or self._admitting)

    def close(self):
        """Stop the engine; queued and running requests fail with
        :class:`ServingError` (idempotent). For a graceful handover
        call :meth:`drain` first."""
        with self._cond:
            if not self._alive:
                return
            self._alive = False
            orphans = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for r in orphans:
            r.fail(ServingError("generation engine shut down before "
                                "dispatch"))
        if self._thread.is_alive() and \
                threading.current_thread() is not self._thread:
            self._thread.join(timeout=10.0)
        for s in list(self._seqs):
            s.table.release()
            if not s.req.done:
                s.req.fail(ServingError("generation engine shut down "
                                        "mid-flight"))
        del self._seqs[:]
        if self._spec is not None:
            self._spec.close()
            self._spec = None
        if self._prefix is not None:
            self._prefix.clear()
            self._prefix = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- admission ------------------------------------------------------------
    def _reserve_tokens(self, req):
        """Cache positions ``req`` needs up front — the ONE encoding of
        the reserve policy, shared by admission (page arithmetic) and
        ``_start`` (actual allocation) so they cannot disagree:
        ``full`` holds the whole generation budget, ``prompt`` only the
        prefill (later growth may preempt)."""
        if self.reserve == "full":
            return len(req.pending_prompt) + req.budget_left
        return len(req.pending_prompt)

    def _reservation(self, req):
        """Pages admission must see free before ``req`` may start —
        EFFECTIVE (dedup-aware): leading full prompt pages already in
        the prefix cache will be pinned, not allocated, so they do not
        draw on the free list. The partial tail page is never
        discounted even when cached — copy-on-write buys it back at
        the first generated token, so counting it would overdraw the
        pool by one page per request. Exhaustion and the submit-time
        shed stay priced in PHYSICAL pages (``can_fit``)."""
        pages = pages_for(self._reserve_tokens(req), self.pool.page_tokens)
        if req.handoff is None and self._prefix is not None:
            pages -= self._prefix.probe(req.pending_prompt)
        return max(pages, 0)

    def _admit(self):
        """Move queued requests into free slots while their reservation
        fits (FIFO — a big head request waits rather than starve)."""
        while True:
            with self._cond:
                if not self._queue or not self._free_slots:
                    return
                req = self._queue[0]
                if AdmissionController.expired(req):
                    self._queue.popleft()
                    self._shed_deadline(req)
                    continue
                if self._reservation(req) > self.pool.available:
                    return
                self._queue.popleft()
                slot = self._free_slots.pop(0)
                self._admitting += 1
            try:
                self._start(req, slot)
            except PoolExhausted as e:
                # raced another consumer of the pool (shouldn't happen
                # with one engine thread, but the accounting is shared):
                # put both back and retry next iteration
                with self._cond:
                    self._queue.appendleft(req)
                    self._free_slots.insert(0, slot)
                    self._free_slots.sort()
                record_event("kv_pool_exhausted", site="serving.generate",
                             action="requeue", model=self.name,
                             error=repr(e))
                return
            finally:
                with self._cond:
                    self._admitting -= 1
                    self._cond.notify_all()

    def _start(self, req, slot):
        """Prefill ``req`` into its freshly allocated block table and
        sample its first token; may retire immediately (budget 1/eos).
        On the fused path the first token is sampled ON DEVICE (its RNG
        counter = the token's position in the full sequence, so a
        preemption resume — which re-prefills prompt+progress —
        continues the stream); only [1] token + logprob cross to the
        host — no [V] logits row."""
        import jax.numpy as jnp
        prompt = req.pending_prompt
        handoff = req.handoff
        table = BlockTable(self.pool)
        matched = 0
        if handoff is None and self._prefix is not None:
            # pin the longest cached page run covering this prompt; a
            # raise here (fault site serving.prefix) degrades the
            # engine to private pages and the request just prefills
            try:
                shared, _covered = self._prefix.match(prompt)
                table.pages.extend(shared)
                matched = len(shared)
            except BaseException as e:
                self._degrade_prefix("match", e)
        try:
            table.ensure(self._reserve_tokens(req))
        except PoolExhausted:
            table.release()   # drops the prefix pins too
            raise
        if self._spec is not None:
            # the paired draft reservation: admit on BOTH pools or on
            # neither (a PoolExhausted here rides the same requeue path
            # as the target's)
            try:
                self._spec.ensure_slot(slot, self._reserve_tokens(req))
            except PoolExhausted:
                self._spec.release_slot(slot)
                table.release()
                raise
        if matched:
            with self._cond:
                self._counts["prefix_hits"] += matched
                self._counts["prefix_hit_requests"] += 1
            self._update_prof(gen_prefix_hits=matched)
        t0 = time.monotonic()
        tok = logp = logits = None
        try:
            fault_point("serving.generate")
            if handoff is not None:
                self._install_handoff(table, handoff)
            else:
                S_b = bucket_for(len(prompt), self._buckets)
                padded = np.zeros((S_b,), np.int32)
                padded[:len(prompt)] = prompt
                if self.device_sample:
                    tok_d, logp_d, self._kp, self._vp = self._prefill_s(
                        self.model.params, self._kp, self._vp,
                        jnp.asarray(padded), np.int32(len(prompt)),
                        jnp.asarray(table.as_row(self.max_blocks)),
                        np.float32(req.temperature),
                        np.int32(req.seed & 0x7FFFFFFF))
                    tok, logp = int(tok_d), float(logp_d)
                else:
                    last, self._kp, self._vp = self._prefill(
                        self.model.params, self._kp, self._vp,
                        jnp.asarray(padded), np.int32(len(prompt)),
                        jnp.asarray(table.as_row(self.max_blocks)))
                    logits = np.asarray(last)
        except BaseException as e:
            table.release()
            if self._spec is not None:
                self._spec.release_slot(slot)
            with self._cond:
                self._free_slots.append(slot)
                self._free_slots.sort()
                self._counts["failed"] += 1
            record_event("generate_failed", site="serving.generate",
                         model=self.name, phase="prefill", error=repr(e))
            self._update_prof(gen_failed=1)
            req.fail(e)
            if self._ensure_pools():
                # the raise consumed the donated pool arrays — every
                # running sequence's cache went with them
                self._fail_running(ServingError(
                    "kv pool arrays lost to a failed prefill: %r" % (e,)))
            return
        self._busy_s += time.monotonic() - t0
        if handoff is None and self._spec is not None:
            # the draft mirrors the prompt into ITS pool; a failure here
            # (fault site serving.speculate) degrades speculation engine
            # wide — the target's prefill already succeeded, so the
            # request keeps running plain. (Handoff requests skip the
            # mirror: they run spec_k=0, so their draft lanes never
            # propose and the draft cache never needs their prompt.)
            try:
                self._spec.prefill(slot, padded, len(prompt))
            except BaseException as e:
                self._degrade_spec("prefill", e)
        if handoff is None and self._prefix is not None:
            # publish the freshly written prompt pages (full AND the
            # partial tail) so the next same-prefix request pins them
            try:
                published = self._prefix.publish(prompt, table.pages)
            except BaseException as e:
                self._degrade_prefix("publish", e)
            else:
                if published:
                    with self._cond:
                        self._counts["prefix_published"] += published
                    self._update_prof(gen_prefix_published=published)
        run = _Running(req, slot, table)
        run.cached = len(prompt)
        # A preemption resume on a SPECULATIVE engine discards the
        # prefill's sample: the canonical stream's token at the resume
        # position came from a draft-accept / residual draw (different
        # salt, different distribution), so recording the plain-keyed
        # prefill sample would fork the tempered history. Instead the
        # row re-enters the round loop pending its last emitted token —
        # round boundaries re-derive identically (caps are pure
        # functions of (request, progress)) and the next round replays
        # the exact accept/reject draws.
        resumed_spec = (handoff is None and self._spec is not None
                        and len(req.tokens) > 0)
        if handoff is not None:
            # the artifact's pages cover the ORIGINAL prompt; pending
            # already carries the prefill tier's first token, so the
            # next decode step writes that token's K/V at position
            # len(prompt) - 1 and the RNG stream continues exactly
            # where a local prefill would have left it
            run.cached = len(prompt) - len(req.tokens)
            run.last_token = req.tokens[-1]
            req.handoff = None   # a preemption resumes by re-prefill
        elif resumed_spec:
            run.cached = len(prompt) - 1
            run.last_token = req.tokens[-1]
        with self._cond:
            if handoff is not None:
                self._counts["handoff_installs"] += 1
            else:
                self._counts["prefills"] += 1
                self._counts["prompt_tokens"] += len(prompt)
            if handoff is None and not resumed_spec:
                self._counts["tokens"] += 1   # the prefill's first token
            self._seqs.append(run)
            self._seqs.sort(key=lambda s: s.slot)
            self._max_running_seen = max(self._max_running_seen,
                                         len(self._seqs))
        if handoff is not None:
            self._update_prof(gen_handoff_installs=1,
                              gen_max_running=len(self._seqs))
        elif resumed_spec:
            self._update_prof(gen_prefills=1,
                              gen_max_running=len(self._seqs))
        elif self.device_sample:
            self._update_prof(gen_prefills=1, gen_tokens=1,
                              gen_max_running=len(self._seqs))
            self._record_token(run, tok, logp)
        else:
            self._update_prof(gen_prefills=1, gen_tokens=1,
                              gen_max_running=len(self._seqs),
                              gen_host_logit_syncs=1)
            with self._cond:
                self._counts["host_logit_syncs"] += 1
            self._accept_token(run, logits)

    # -- the fused decode step ------------------------------------------------
    def _step(self):
        if self._spec is not None:
            self._step_spec()
            return
        import jax.numpy as jnp
        self._grow_tables()
        seqs = list(self._seqs)
        if not seqs:
            return
        R, MB = self.max_running, self.max_blocks
        tables = np.full((R, MB), self.pool.trash_page, np.int32)
        positions = np.zeros((R,), np.int32)
        tokens = np.zeros((R,), np.int32)
        active = np.zeros((R,), bool)
        fused = self.device_sample
        if fused:
            temps = np.zeros((R,), np.float32)
            seeds = np.zeros((R,), np.int32)
        for s in seqs:
            tables[s.slot] = s.table.as_row(MB)
            positions[s.slot] = s.cached
            tokens[s.slot] = s.last_token
            active[s.slot] = True
            if fused:
                temps[s.slot] = s.req.temperature
                seeds[s.slot] = s.req.seed & 0x7FFFFFFF
        t0 = time.monotonic()
        try:
            fault_point("serving.generate")
            if fused:
                # temps/seeds only change when the running SET changes
                # (admit/retire/preempt), so their device copies are
                # cached — the fused step uploads the same operands per
                # step as the host path; each row's RNG counter is
                # derived on device as positions + 1 (= its token
                # offset, which RESUMES after preemption)
                cached = self._sample_meta
                if (cached is None
                        or not np.array_equal(temps, cached[0])
                        or not np.array_equal(seeds, cached[1])):
                    cached = (temps, seeds, jnp.asarray(temps),
                              jnp.asarray(seeds))
                    self._sample_meta = cached
                packed, self._kp, self._vp = self._decode_s(
                    self.model.params, self._kp, self._vp,
                    jnp.asarray(tables), jnp.asarray(positions),
                    jnp.asarray(tokens), jnp.asarray(active),
                    cached[2], cached[3])
                packed = np.asarray(packed)
                tok_rows = packed[:R].astype(np.int32)
                logp_rows = packed[R:]
            else:
                logits, self._kp, self._vp = self._decode(
                    self.model.params, self._kp, self._vp,
                    jnp.asarray(tables), jnp.asarray(positions),
                    jnp.asarray(tokens), jnp.asarray(active))
                rows = np.asarray(logits)
        except BaseException as e:
            self._fail_running(e)
            self._ensure_pools()
            return
        self._busy_s += time.monotonic() - t0
        util = self.pool.utilization()["frac"]
        # token counters flush ONCE per fused step (every running row
        # accepts exactly one token below) — per-row updates on the hot
        # loop are the profiler contract violation its docstring names
        kernel_hit = 1 if self.attn_config else 0
        with self._cond:
            self._counts["decode_steps"] += 1
            self._counts["tokens"] += len(seqs)
            self._counts["kernel_hits"] += kernel_hit
            self._counts["device_sample_steps" if fused
                          else "host_logit_syncs"] += 1
            self._occupancy_sum += len(seqs)
            self._page_util_max = max(self._page_util_max, util)
        prof = {"gen_decode_steps": 1, "gen_page_util_max": util,
                "gen_tokens": len(seqs), "gen_kernel_hits": kernel_hit}
        prof["gen_device_sample_steps" if fused
             else "gen_host_logit_syncs"] = 1
        self._update_prof(**prof)
        for s in seqs:
            s.cached += 1
            if fused:
                self._record_token(s, int(tok_rows[s.slot]),
                                   float(logp_rows[s.slot]))
            else:
                self._accept_token(s, rows[s.slot])

    # -- the speculative round ------------------------------------------------
    def _grow_tables_spec(self):
        """Speculative variant of :meth:`_grow_tables`: grow BOTH pools
        to the row's round window (cached + cap + 1), where ``cap`` —
        the number of draft lanes the row runs this round — is a PURE
        function of the request and its progress (engine k, per-request
        k, remaining budget, context clamp). Purity is what makes the
        tempered accept/reject stream replay bit-exactly across
        preemption: a resume re-derives identical round boundaries from
        prompt+progress. Pool starvation therefore preempts or sheds
        through the normal machinery — it must never quietly shrink one
        row's cap."""
        for s in list(self._seqs):
            req_k = (s.req.spec_k if s.req.spec_k is not None
                     else self.spec_k)
            cap = max(0, min(self.spec_k, req_k, s.req.budget_left - 1,
                             self.max_context - 1 - s.cached))
            try:
                s.table.ensure(s.cached + cap + 1)
                self._spec.ensure_slot(s.slot, s.cached + cap + 1)
                # the verify step rewrites position s.cached and writes
                # up to cap+1 new ones — unshare every covering page
                self._unshare_for_write(s.table, s.cached,
                                        s.cached + cap + 1)
            except PoolExhausted:
                if len(self._seqs) > 1 and \
                        s.req.preemptions < _PREEMPT_LIMIT:
                    self._preempt(s)
                else:
                    self._shed_pool(s)
                continue
            s.spec_cap = cap

    def _step_spec(self):
        """One speculative round for the whole running batch: the draft
        proposes up to k tokens per row (its own pool, ONE dispatch),
        the target verifies every lane in ONE fused step, and the host
        does pure bookkeeping — consume the accepted prefix plus the
        correction/bonus token, then roll the page overshoot back to
        both pools (``BlockTable.trim``; cache CONTENTS never roll
        back, see kvcache). A propose failure degrades speculation and
        skips the round (the loop re-steps plain); a verify failure
        follows the plain step's serving.generate decode contract."""
        import jax.numpy as jnp
        self._grow_tables_spec()
        seqs = list(self._seqs)
        if not seqs:
            return
        R, MB = self.max_running, self.max_blocks
        MBd = self._spec.max_blocks
        K1 = self.spec_k + 1
        tables = np.full((R, MB), self.pool.trash_page, np.int32)
        dtables = np.full((R, MBd), self._spec.pool.trash_page, np.int32)
        positions = np.zeros((R,), np.int32)
        tokens = np.zeros((R,), np.int32)
        active = np.zeros((R,), bool)
        temps = np.zeros((R,), np.float32)
        seeds = np.zeros((R,), np.int32)
        caps = np.zeros((R,), np.int32)
        for s in seqs:
            tables[s.slot] = s.table.as_row(MB)
            dtables[s.slot] = self._spec.row(s.slot)
            positions[s.slot] = s.cached
            tokens[s.slot] = s.last_token
            active[s.slot] = True
            temps[s.slot] = s.req.temperature
            seeds[s.slot] = s.req.seed & 0x7FFFFFFF
            caps[s.slot] = s.spec_cap
        t0 = time.monotonic()
        try:
            fault_point("serving.generate")
            cached = self._sample_meta
            if (cached is None
                    or not np.array_equal(temps, cached[0])
                    or not np.array_equal(seeds, cached[1])):
                cached = (temps, seeds, jnp.asarray(temps),
                          jnp.asarray(seeds))
                self._sample_meta = cached
            pos_d = jnp.asarray(positions)
            tok_d = jnp.asarray(tokens)
            act_d = jnp.asarray(active)
            caps_d = jnp.asarray(caps)
            try:
                drafts, dlogits = self._spec.propose(
                    jnp.asarray(dtables), pos_d, tok_d, act_d,
                    cached[2], cached[3], caps_d)
            except BaseException as pe:
                self._degrade_spec("propose", pe)
                return
            packed, self._kp, self._vp = self._verify_s(
                self.model.params, self._kp, self._vp,
                jnp.asarray(tables), pos_d, tok_d, drafts, dlogits,
                act_d, cached[2], cached[3], caps_d)
            packed = np.asarray(packed)
        except BaseException as e:
            self._fail_running(e)
            self._ensure_pools()
            return
        self._busy_s += time.monotonic() - t0
        tok_rows = packed[:, :K1].astype(np.int32)
        n_out = packed[:, K1].astype(np.int32)
        logp_rows = packed[:, K1 + 1:]
        drafted = int(sum(s.spec_cap for s in seqs))
        accepted = int(sum(max(int(n_out[s.slot]) - 1, 0) for s in seqs))
        consumed = 0
        for s in seqs:
            for j in range(int(n_out[s.slot])):
                if s.req.done:
                    break   # retired mid-round; the tail is discarded
                s.cached += 1
                consumed += 1
                self._record_token(s, int(tok_rows[s.slot, j]),
                                   float(logp_rows[s.slot, j]))
            if s.req.done:
                continue
            # roll the speculation overshoot back to both pools: pages
            # past what the accepted point (plus the reserve policy's
            # floor) needs are free again before the next admission
            floor = max(s.cached + 1, self._reserve_tokens(s.req))
            s.table.trim(floor)
            self._spec.trim_slot(s.slot, floor)
        util = self.pool.utilization()["frac"]
        kernel_hit = 1 if self.attn_config else 0
        with self._cond:
            self._counts["decode_steps"] += 1
            self._counts["spec_steps"] += 1
            self._counts["tokens"] += consumed
            self._counts["draft_tokens"] += drafted
            self._counts["accepted_tokens"] += accepted
            self._counts["kernel_hits"] += kernel_hit
            self._counts["device_sample_steps"] += 1
            self._occupancy_sum += len(seqs)
            self._page_util_max = max(self._page_util_max, util)
        self._update_prof(
            gen_decode_steps=1, gen_page_util_max=util,
            gen_tokens=consumed, gen_kernel_hits=kernel_hit,
            gen_device_sample_steps=1, gen_spec_steps=1,
            gen_draft_tokens=drafted, gen_accepted_tokens=accepted)

    def _degrade_spec(self, phase, exc):
        """Speculation failed (fault site ``serving.speculate``): drop
        the draft engine and keep serving plain fused decode — a
        recorded perf regression, never an outage. Running sequences
        are unharmed: the draft pool is the only state a draft failure
        can consume, and the target's cache never depended on it."""
        spec = self._spec
        if spec is None:
            return
        self._spec = None
        self._spec_degraded = True
        try:
            spec.close()
        except Exception:
            pass
        record_event("speculation_degraded", site="serving.speculate",
                     model=self.name, phase=phase, error=repr(exc))
        self._update_prof(gen_spec_degraded=1)

    def _degrade_prefix(self, phase, exc):
        """Prefix sharing failed (fault site ``serving.prefix``): drop
        the cache and keep serving plain private pages — a
        memory-economics regression, never an outage. Running tables
        that already share pages between THEMSELVES keep them (the
        copy-on-write check in ``_unshare_for_write`` runs regardless
        of the cache, so shared history stays safe to the end)."""
        cache = self._prefix
        if cache is None:
            return
        self._prefix = None
        self._prefix_degraded = True
        try:
            cache.clear()
        except Exception:
            pass
        record_event("prefix_degraded", site="serving.prefix",
                     model=self.name, phase=phase, error=repr(exc))
        self._update_prof(gen_prefix_degraded=1)

    def _unshare_for_write(self, table, start, upto):
        """Copy-on-write: before the step writes positions
        ``[start, upto)``, any covering page that is still SHARED
        (another table or the prefix cache pins it) is replaced by a
        fresh device copy — ONE page allocated and copied
        (``kp.at[:, new].set(kp[:, old])`` under donation), the shared
        original stays pristine for everyone else. May raise
        :class:`PoolExhausted` mid-walk (the caller's preempt/shed
        machinery decides); pages already copied stay consistently
        private, so a later resume is unaffected."""
        T = self.pool.page_tokens
        last = min((upto - 1) // T + 1, len(table.pages))
        copies = 0
        for i in range(start // T, last):
            old = table.pages[i]
            if self.pool.refcount(old) <= 1:
                continue
            new = self.pool.alloc(1)[0]
            if self._cow is None:
                import jax

                def _cow_fn(kp, vp, src, dst):
                    return (kp.at[:, dst].set(kp[:, src]),
                            vp.at[:, dst].set(vp[:, src]))
                self._cow = jax.jit(_cow_fn, donate_argnums=(0, 1))
            self._kp, self._vp = self._cow(self._kp, self._vp,
                                           np.int32(old), np.int32(new))
            table.pages[i] = new
            self.pool.free([old])
            copies += 1
        if copies:
            with self._cond:
                self._counts["cow_copies"] += copies
            self._update_prof(gen_cow_copies=copies)

    def _install_handoff(self, table, artifact):
        """The decode tier's receive side of the disaggregated hop
        (serving/disagg.py): scatter the artifact's exported K/V page
        contents into this pool at the table's freshly allocated ids.
        Fixed-shape — ids trash-padded to ``max_blocks``, contents
        zero-padded — so the face compiles once. On CPU this is a
        host->device copy of the whole padded block; a real TPU
        deployment would DMA the pages directly (doc/serving.md spells
        out the honest caveat)."""
        import jax
        import jax.numpy as jnp
        k, v = artifact.k_pages, artifact.v_pages
        pool = self.pool
        n = int(k.shape[1])
        expect = (pool.num_layers, n, pool.page_tokens, pool.num_heads,
                  pool.head_dim)
        if tuple(k.shape) != expect or tuple(v.shape) != expect:
            raise ServingError(
                "handoff page content shape %r/%r does not match the "
                "pool layout %r" % (tuple(k.shape), tuple(v.shape),
                                    expect))
        if n > len(table.pages):
            raise ServingError(
                "handoff carries %d page(s) but the table only holds "
                "%d" % (n, len(table.pages)))
        if self._install is None:
            def _install_fn(kp, vp, ids, kc, vc):
                return kp.at[:, ids].set(kc), vp.at[:, ids].set(vc)
            self._install = jax.jit(_install_fn, donate_argnums=(0, 1))
        MB = self.max_blocks
        ids = np.full((MB,), pool.trash_page, np.int32)
        ids[:n] = table.pages[:n]
        shape = (pool.num_layers, MB, pool.page_tokens, pool.num_heads,
                 pool.head_dim)
        kc = np.zeros(shape, np.asarray(k).dtype)
        vc = np.zeros(shape, kc.dtype)
        kc[:, :n] = k
        vc[:, :n] = v
        self._kp, self._vp = self._install(
            self._kp, self._vp, jnp.asarray(ids), jnp.asarray(kc),
            jnp.asarray(vc))

    def _ensure_pools(self):
        """A raise from INSIDE a donated jitted call (device OOM,
        XlaRuntimeError) consumes the pool arrays before it surfaces —
        without this, every later prefill/decode would hit
        'Array has been deleted' and the engine would fail forever
        while claiming to keep serving. Rebuild the arrays when that
        happened; the caller must already have failed every sequence
        whose cache lived in the lost buffers. Returns True when a
        rebuild was needed."""
        deleted = getattr(self._kp, "is_deleted", None)
        if deleted is None or not deleted():
            return False
        self._kp, self._vp = self.pool.zeros()
        if self._prefix is not None:
            # cached prefix contents died with the arrays — a stale
            # entry would splice zero pages into someone's prompt
            self._prefix.reset()
        self._check_pool_install("serving.engine_pool_rebuild")
        return True

    def _check_pool_install(self, entry):
        """Donation-aliasing sanitizer choke point
        (``PADDLE_TPU_SANITIZE=alias``): the K/V pool arrays ride every
        prefill/decode call at DONATED positions — a numpy-backed buffer
        installed here is exactly the zero-copy-alias-then-free shape
        the executor and checkpoint guards exist for."""
        from ..analysis.sanitize import check_donated
        check_donated({"k_pages": self._kp, "v_pages": self._vp}, entry)

    def _grow_tables(self):
        """Make room for each running row's next position — and
        copy-on-write any still-shared page the write would land in;
        starvation preempts (or sheds, when preemption cannot help)."""
        for s in list(self._seqs):
            try:
                s.table.ensure(s.cached + 1)
                self._unshare_for_write(s.table, s.cached, s.cached + 1)
            except PoolExhausted:
                if len(self._seqs) > 1 and \
                        s.req.preemptions < _PREEMPT_LIMIT:
                    self._preempt(s)
                else:
                    self._shed_pool(s)

    def _evict(self, s, counter=None, requeue=False):
        """The one eviction primitive: release the row's pages, recycle
        its slot, optionally bump a counter / re-queue its request
        (front), and wake drain()/admission waiters. Every path that
        removes a running sequence — retire, preempt, shed, deadline,
        step failure — MUST come through here so the lock discipline
        and free-slot ordering cannot drift apart. What happens to the
        request afterwards (resolve/fail) is the caller's job."""
        s.table.release()
        if self._spec is not None:
            self._spec.release_slot(s.slot)
        with self._cond:
            if s in self._seqs:
                self._seqs.remove(s)
            self._free_slots.append(s.slot)
            self._free_slots.sort()
            if counter is not None:
                self._counts[counter] += 1
            if requeue:
                self._queue.appendleft(s.req)
            self._cond.notify_all()

    def _preempt(self, s):
        """Recompute-on-resume: free the row's pages and re-queue the
        request (front) carrying its progress — greedy decode re-derives
        the same continuation from prompt+progress, so preemption is
        invisible in the output stream."""
        record_event("kv_pool_exhausted", site="serving.generate",
                     action="preempt", model=self.name,
                     generated=len(s.req.tokens),
                     preemptions=s.req.preemptions + 1)
        s.req.preemptions += 1
        self._evict(s, counter="preemptions", requeue=True)
        self._update_prof(gen_preemptions=1)

    def _shed_pool(self, s):
        record_event("kv_pool_exhausted", site="serving.generate",
                     action="shed", model=self.name,
                     generated=len(s.req.tokens))
        self._evict(s, counter="shed_pool")
        self._update_prof(gen_shed_pool=1)
        s.req.fail(PoolExhausted(
            "kv page pool exhausted mid-flight after %d generated "
            "token(s) and preemption could not help — shrink "
            "max_new_tokens, raise FLAGS.serve_kv_pages, or use "
            "reserve='full' admission" % len(s.req.tokens)))

    # -- sampling / retirement ------------------------------------------------
    def _accept_token(self, s, logits):
        """Host-sampling path: sample from the materialized [V] logits
        row, then book-keep."""
        tok = sample_token(logits, s.req.temperature, s.req._rng)
        self._record_token(s, tok, None)

    def _record_token(self, s, tok, logp=None):
        """Pure bookkeeping for ONE accepted token — the whole host-side
        job of the fused path: append (token, logprob), stamp latency,
        and retire on eos/length/deadline straight off the returned
        token, never off re-materialized logits."""
        req = s.req
        now = time.monotonic()
        req.tokens.append(tok)
        if logp is not None:
            req.logprobs.append(logp)
        s.last_token = tok
        if req._ttft_ms is None:
            req._ttft_ms = (now - req.enqueue_t) * 1e3
            self._ttft_ms.append(req._ttft_ms)
        else:
            self._intertoken_ms.append((now - s.last_t) * 1e3)
        s.last_t = now
        if self.eos_id is not None and tok == self.eos_id:
            self._retire(s, "eos")
        elif req.budget_left <= 0:
            self._retire(s, "length")
        elif AdmissionController.expired(req):
            self._retire_deadline(s)

    def _retire(self, s, reason):
        """Finish a sequence NOW: its pages and slot recycle into the
        very next admission — the continuous half of the batching."""
        self._evict(s, counter="completed")
        self._update_prof(gen_completed=1)
        s.req.resolve(reason)

    def _retire_deadline(self, s):
        self._evict(s)
        self._shed_deadline(s.req, generated=len(s.req.tokens))

    def _shed_deadline(self, req, generated=0):
        late_ms = (time.monotonic() - req.deadline_t) * 1e3
        record_event("request_shed", site="serving.generate",
                     reason="deadline", model=self.name, late_ms=late_ms,
                     generated=generated)
        with self._cond:
            self._counts["shed_deadline"] += 1
        self._update_prof(gen_shed_deadline=1)
        req.fail(DeadlineExceededError(
            "generation deadline exceeded %.1f ms ago (%d token(s) "
            "generated); shed instead of serving a dead client"
            % (late_ms, generated)))

    def _fail_running(self, exc):
        """A raise at the fused step fails the RUNNING sequences (their
        cache rows are suspect) and the loop keeps serving — the
        batcher's batch_failed contract, generation-shaped."""
        seqs = list(self._seqs)
        if not seqs:
            return
        record_event("generate_failed", site="serving.generate",
                     model=self.name, phase="decode",
                     sequences=len(seqs), error=repr(exc))
        for s in seqs:
            self._evict(s, counter="failed")
            s.req.fail(exc)
        self._update_prof(gen_failed=len(seqs))

    # -- metrics --------------------------------------------------------------
    @staticmethod
    def _trace_count(fn):
        """Compiled-trace count via the jit wrapper's cache probe — a
        private jax surface (no public one exists), so degrade to -1
        when a jax bump renames it rather than 500-ing every /statz."""
        probe = getattr(fn, "_cache_size", None)
        try:
            return int(probe()) if probe is not None else -1
        except Exception:
            return -1

    @staticmethod
    def _update_prof(**kw):
        from .. import profiler as _prof
        _prof.update_generation_counters(**kw)

    @property
    def stats(self):
        """Snapshot of the generation metrics surface."""
        with self._cond:
            c = dict(self._counts)
            steps = c.get("decode_steps", 0)
            ttft = list(self._ttft_ms)
            itl = list(self._intertoken_ms)
            snap = {
                "submitted": c.get("submitted", 0),
                "completed": c.get("completed", 0),
                "failed": c.get("failed", 0),
                "shed_overload": c.get("shed_overload", 0),
                "shed_deadline": c.get("shed_deadline", 0),
                "shed_pool": c.get("shed_pool", 0),
                "preemptions": c.get("preemptions", 0),
                "prefills": c.get("prefills", 0),
                "decode_steps": steps,
                "tokens_generated": c.get("tokens", 0),
                "prompt_tokens": c.get("prompt_tokens", 0),
                "queued": len(self._queue),
                "running": len(self._seqs),
                "max_running": self.max_running,
                "max_running_seen": self._max_running_seen,
                "running_occupancy": (self._occupancy_sum / steps
                                      if steps else 0.0),
                "page_utilization": self.pool.utilization(),
                "page_utilization_max": self._page_util_max,
                "ttft_ms_p50": _percentile(ttft, 0.50),
                "ttft_ms_p99": _percentile(ttft, 0.99),
                "intertoken_ms_p50": _percentile(itl, 0.50),
                "intertoken_ms_p99": _percentile(itl, 0.99),
                "tokens_per_s": (c.get("tokens", 0) / self._busy_s
                                 if self._busy_s > 0 else 0.0),
                "device_sample": self.device_sample,
                "device_sample_steps": c.get("device_sample_steps", 0),
                "host_logit_syncs": c.get("host_logit_syncs", 0),
                "attn_kernel": bool(self.attn_config),
                "kernel_hits": c.get("kernel_hits", 0),
                "prefix_sharing": self._prefix is not None,
                "prefix_degraded": self._prefix_degraded,
                "prefix_hits": c.get("prefix_hits", 0),
                "prefix_hit_requests": c.get("prefix_hit_requests", 0),
                "prefix_published": c.get("prefix_published", 0),
                "cow_copies": c.get("cow_copies", 0),
                "prefix_cache": (self._prefix.stats()
                                 if self._prefix is not None else None),
                "handoff_installs": c.get("handoff_installs", 0),
                "page_release_rate": self.pool.release_rate(),
                "speculative": self._spec is not None,
                "spec_k": self.spec_k,
                "spec_degraded": self._spec_degraded,
                "spec_steps": c.get("spec_steps", 0),
                "draft_tokens": c.get("draft_tokens", 0),
                "accepted_tokens": c.get("accepted_tokens", 0),
                "acceptance_rate": (
                    c.get("accepted_tokens", 0)
                    / float(c.get("draft_tokens", 0))
                    if c.get("draft_tokens", 0) else 0.0),
                "spec_verify_traces": (
                    self._trace_count(self._verify_s)
                    if self._verify_s is not None else 0),
                "spec_propose_traces": (
                    self._spec.propose_traces
                    if self._spec is not None else 0),
                "draft_page_utilization": (
                    self._spec.pool.utilization()
                    if self._spec is not None else None),
                # the ACTIVE faces' trace counts — the compiled-once
                # contract is on the path actually serving
                "decode_traces": self._trace_count(
                    self._decode_s if self.device_sample
                    else self._decode),
                "prefill_traces": self._trace_count(
                    self._prefill_s if self.device_sample
                    else self._prefill),
            }
        snap["shed"] = (snap["shed_overload"] + snap["shed_deadline"]
                        + snap["shed_pool"])
        return snap
