"""Admission control: who gets in the queue, who gets shed, and why.

An online service's failure mode is not a crash — it is a convoy: a
burst outruns the device, the queue grows, every request's latency
inherits the whole backlog, and by the time the backlog drains the
clients have timed out anyway. The controls here are the standard three
(the reference leaves this to however many pserver/capi threads the
operator configured; here it is explicit policy):

- **queue-depth backpressure** — at most ``queue_depth`` requests may
  wait for dispatch; request ``queue_depth + 1`` is rejected *now* with
  :class:`OverloadError` instead of queuing into certain lateness.
- **per-request deadlines** — a request carrying ``deadline_ms`` that is
  already late when the dispatcher reaches it is shed with
  :class:`DeadlineExceededError` rather than burned device time on (the
  client stopped listening; serving it helps nobody).
- **shed accounting** — every shed is a recorded
  ``paddle_tpu.resilience`` degradation event (``request_shed``), so "we
  dropped load" is auditable the same way checkpoint fallbacks and
  degraded pserver modes are, and chaos specs can assert on it.
"""
from __future__ import annotations

import time

from ..resilience import record_event

__all__ = ["ServingError", "OverloadError", "DeadlineExceededError",
           "ModelUnavailableError", "AdmissionController"]


class ServingError(RuntimeError):
    """Base of the serving tier's request-rejection errors."""


class OverloadError(ServingError):
    """Shed at admission: the bounded request queue is full."""


class DeadlineExceededError(ServingError):
    """Shed at dispatch: the request's deadline passed while it queued."""


class ModelUnavailableError(ServingError):
    """No model (or no live version) registered under the requested name."""


class AdmissionController(object):
    """Policy object consulted by the service/batcher at the two shed
    points. Stateless beyond its knobs — the queue it bounds lives in
    the batcher, whose lock makes the depth check exact."""

    def __init__(self, queue_depth):
        self.queue_depth = max(int(queue_depth), 1)

    # -- admission (called under the batcher's queue lock) -------------------
    def check_queue(self, pending, model=None):
        """Raise :class:`OverloadError` when ``pending`` queued requests
        leave no room for one more; records the shed."""
        if pending >= self.queue_depth:
            record_event("request_shed", site="serving.admission",
                         reason="overload", model=model,
                         queue_depth=self.queue_depth)
            raise OverloadError(
                "serving queue full (%d pending >= queue_depth=%d); "
                "request shed — retry with backoff or raise "
                "FLAGS.serve_queue_depth" % (pending, self.queue_depth))

    # -- deadlines -----------------------------------------------------------
    @staticmethod
    def deadline_from(deadline_ms, now=None):
        """Absolute monotonic deadline for a relative ``deadline_ms``
        budget (None = no deadline)."""
        if deadline_ms is None:
            return None
        now = time.monotonic() if now is None else now
        return now + float(deadline_ms) / 1e3

    @staticmethod
    def expired(request, now=None):
        if request.deadline_t is None:
            return False
        now = time.monotonic() if now is None else now
        return now > request.deadline_t

    def shed_deadline(self, request, now=None):
        """Fail an expired request with a recorded degradation event."""
        now = time.monotonic() if now is None else now
        late_ms = (now - request.deadline_t) * 1e3
        record_event("request_shed", site="serving.dispatch",
                     reason="deadline", model=request.model,
                     late_ms=late_ms)
        request.fail(DeadlineExceededError(
            "request deadline exceeded %.1f ms before dispatch "
            "(model %r); shed instead of serving a dead client"
            % (late_ms, request.model)))
