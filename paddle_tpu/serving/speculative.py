"""Speculative decoding's draft side: a second engine inside the first.

One speculative round replaces one fused decode step: the DRAFT model —
small, cheap, same vocabulary — proposes ``k`` tokens autoregressively
(``models/transformer.draft_propose_step``, a lax.scan of k+1 decode
substeps in ONE jit dispatch), then the TARGET model verifies all k+1
positions in a single fused step (``verify_step_sampled``) that accepts
the longest valid draft prefix and samples the correction/bonus token on
device. Greedy output is token-identical to non-speculative decode (the
hard gate — longest-matching-prefix + argmax correction reconstructs the
plain greedy sequence exactly); tempered rows use canonical rejection
sampling keyed by the position-keyed fold_in stream, so a preemption
resume replays the exact accept/reject history.

This module owns everything drafted: the draft model's OWN
:class:`~paddle_tpu.serving.kvcache.PagePool` and per-slot
:class:`BlockTable`\\ s (sized by the same allocator as the target's —
same page_tokens, same loud free discipline), the jitted propose and
prefill faces, and their warm-up. The proposals and draft logits it
returns are DEVICE arrays handed straight to the target's verify jit —
no draft logits row ever crosses to the host, so the engine's
``gen_host_logit_syncs == 0`` invariant survives speculation.

Fault site ``serving.speculate`` (armable): it guards the draft-engine
build, the draft prefill, and every propose call. A raise anywhere here
is a PERF regression, never an outage — the generation engine records a
``speculation_degraded`` event, drops the draft engine, and keeps
serving plain fused decode; running sequences are unharmed because the
draft pool is the only state a propose failure can consume.
"""
from __future__ import annotations

import numpy as np

from ..resilience import fault_point
from .kvcache import BlockTable, PagePool, pages_for

__all__ = ["DraftEngine"]


def _trace_count(fn):
    """Compiled-trace count via the jit cache probe (same degrade-to--1
    contract as the generation engine's)."""
    probe = getattr(fn, "_cache_size", None)
    try:
        return int(probe()) if probe is not None else -1
    except Exception:
        return -1


class DraftEngine(object):
    """The draft half of a speculative generation engine.

    Owned by a :class:`~paddle_tpu.serving.generator.GenerationEngine`
    and driven only from its engine thread (the pool arrays are donated
    through the propose jit exactly like the target's — single-owner
    discipline). ``kv_pages``/``page_tokens`` mirror the target pool's
    geometry so a reservation that admits on the target admits here
    too; a draft-side exhaustion mid-flight preempts the row through
    the normal machinery.
    """

    def __init__(self, model, k, target_config, kv_pages, page_tokens,
                 max_context, buckets, name="model"):
        import jax
        fault_point("serving.speculate")
        k = int(k)
        if k < 1:
            raise ValueError("speculation depth k must be >= 1, got %d"
                             % k)
        dc = model.config
        if dc.vocab_size != target_config.vocab_size:
            raise ValueError(
                "draft vocab_size=%d != target vocab_size=%d — "
                "speculative accept compares token ids, the "
                "vocabularies must be identical"
                % (dc.vocab_size, target_config.vocab_size))
        if dc.max_seq < int(max_context):
            raise ValueError(
                "draft max_seq=%d < target context window %d — the "
                "draft must cover every position it proposes at"
                % (dc.max_seq, int(max_context)))
        self.model = model
        self.k = k
        self.name = name
        self.max_context = int(max_context)
        self.max_blocks = pages_for(self.max_context, page_tokens)
        L, nh, dh = model.kv_spec
        self.pool = PagePool(kv_pages, page_tokens, L, nh, dh)
        self._kp, self._vp = self.pool.zeros()
        self._check_pool_install("serving.draft_pool_install")
        self._propose = jax.jit(model.draft_propose_fn(k),
                                donate_argnums=(1, 2))
        self._prefill = jax.jit(model.prefill_fn(), donate_argnums=(1, 2))
        self._buckets = list(buckets)
        self._tables = {}   # slot -> BlockTable (draft pool)

    # -- per-slot block tables ----------------------------------------------
    def ensure_slot(self, slot, tokens):
        """Grow (creating if needed) slot's draft table to hold
        ``tokens`` positions; raises PoolExhausted allocating nothing."""
        t = self._tables.get(slot)
        if t is None:
            t = self._tables[slot] = BlockTable(self.pool)
        t.ensure(tokens)

    def trim_slot(self, slot, tokens):
        """Roll back slot's speculation-overshoot pages (see
        ``BlockTable.trim``)."""
        t = self._tables.get(slot)
        return t.trim(tokens) if t is not None else 0

    def release_slot(self, slot):
        """Free slot's draft pages (idempotent — eviction rides this)."""
        t = self._tables.pop(slot, None)
        if t is not None:
            t.release()

    def release_all(self):
        for slot in list(self._tables):
            self.release_slot(slot)

    def row(self, slot):
        return self._tables[slot].as_row(self.max_blocks)

    # -- jitted faces --------------------------------------------------------
    def prefill(self, slot, padded, length):
        """Scatter one prompt's K/V into the draft pool (bucketed like
        the target prefill; the logits never leave the device)."""
        import jax.numpy as jnp
        fault_point("serving.speculate")
        _, self._kp, self._vp = self._prefill(
            self.model.params, self._kp, self._vp, jnp.asarray(padded),
            np.int32(length), jnp.asarray(self.row(slot)))

    def propose(self, tables, positions, tokens, active, temperatures,
                seeds, spec_caps):
        """One k-token proposal round for the whole running batch.
        Returns (drafts [R, k], draft_logits [R, k, V]) as DEVICE
        arrays — they feed the target's verify jit directly."""
        fault_point("serving.speculate")
        drafts, draft_logits, self._kp, self._vp = self._propose(
            self.model.params, self._kp, self._vp, tables, positions,
            tokens, active, temperatures, seeds, spec_caps)
        return drafts, draft_logits

    def warm(self, max_running):
        """Pre-trigger the draft compiles with all-trash tables (every
        prefill bucket + the propose face). Returns the warm propose's
        (drafts, draft_logits) device arrays so the caller can feed its
        verify warm-up without a second propose."""
        import jax.numpy as jnp
        trash_row = np.full((self.max_blocks,), self.pool.trash_page,
                            np.int32)
        for S_b in self._buckets:
            _, self._kp, self._vp = self._prefill(
                self.model.params, self._kp, self._vp,
                jnp.asarray(np.zeros((S_b,), np.int32)), np.int32(1),
                jnp.asarray(trash_row))
        R = int(max_running)
        zeros_i = jnp.asarray(np.zeros((R,), np.int32))
        drafts, draft_logits, self._kp, self._vp = self._propose(
            self.model.params, self._kp, self._vp,
            jnp.asarray(np.tile(trash_row, (R, 1))), zeros_i, zeros_i,
            jnp.asarray(np.zeros((R,), bool)),
            jnp.asarray(np.zeros((R,), np.float32)), zeros_i, zeros_i)
        return drafts, draft_logits

    # -- plumbing ------------------------------------------------------------
    def _check_pool_install(self, entry):
        # same donation-aliasing sanitizer choke point as the target
        # pool (PADDLE_TPU_SANITIZE=alias)
        from ..analysis.sanitize import check_donated
        check_donated({"k_pages": self._kp, "v_pages": self._vp}, entry)

    def ensure_pools(self):
        """Rebuild the draft pool arrays if a raise consumed them (the
        target engine's ``_ensure_pools`` contract, draft-shaped)."""
        deleted = getattr(self._kp, "is_deleted", None)
        if deleted is None or not deleted():
            return False
        self._kp, self._vp = self.pool.zeros()
        self._check_pool_install("serving.draft_pool_rebuild")
        return True

    @property
    def propose_traces(self):
        return _trace_count(self._propose)

    @property
    def prefill_traces(self):
        return _trace_count(self._prefill)

    def stats(self):
        return {"k": self.k,
                "page_utilization": self.pool.utilization(),
                "propose_traces": self.propose_traces,
                "prefill_traces": self.prefill_traces}

    def close(self):
        self.release_all()
