"""Paged KV-cache: the memory system under continuous batching.

A naive autoregressive server gives every sequence a max-length K/V
buffer up front — most of it never used, and the worst sequence bounds
everyone's admission. The paged design (vLLM's PagedAttention, see
PAPERS.md) splits the cache into fixed-size **pages** of
``page_tokens`` positions each, preallocated once per model as one
device-resident pool, and gives each running sequence a **block table**
— the ordered list of page ids its positions live in. Allocation is
O(1) list ops on the host; the device never sees fragmentation because
attention reads K/V *through* the block table (gather) and writes the
new position *through* it (scatter) — see
``models/transformer.decode_step``.

Layout: ``[num_layers, num_pages + 1, page_tokens, heads, head_dim]``
per K and V. The LAST page is the **trash page**: block tables are
padded with it, and writes for inactive batch rows are routed to it, so
every scatter in the jitted step has a fixed shape and a legal target —
no masking branches, no retraces. Trash contents are garbage by design
and are never read unmasked.

Exhaustion is policy, not a crash: ``alloc`` raises
:class:`PoolExhausted` (a :class:`ServingError`), and the generation
engine turns that into the house degrade-and-record convention — a shed
or a preemption with a recorded ``kv_pool_exhausted`` event. The pool
itself never kills anything.

**Sharing** (copy-on-write prefix reuse, ``serving/prefix.py``): every
live page carries a REFCOUNT. ``alloc`` hands out pages at refcount 1;
``ref`` lets another holder (a second BlockTable pinning the same
prompt prefix, or the prefix cache itself) pin the same physical page;
``free`` decrements and returns the page to the free list only at zero.
Accounting therefore splits in two: *physical* pages (what the device
actually holds — the exhaustion policy's unit) and *effective* pages
(sum of refcounts — what the same traffic would cost without sharing).
The pool stays write-dumb: deciding when a shared page must be copied
before a divergent write (CoW) is the engine's job; the pool only
answers ``refcount``/``is_shared``. An optional ``reclaimer`` hook lets
the prefix cache's LRU give unreferenced-but-cached pages back under
allocation pressure before ``alloc`` declares exhaustion.

Knobs: ``FLAGS.serve_kv_pages`` (usable pages in the pool) and
``FLAGS.serve_page_tokens`` (positions per page).
"""
from __future__ import annotations

import collections
import threading
import time

from .admission import ServingError
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["PoolExhausted", "PagePool", "BlockTable", "pages_for"]


class PoolExhausted(ServingError):
    """The page pool cannot satisfy an allocation right now."""


def pages_for(tokens, page_tokens):
    """Pages needed to hold ``tokens`` positions (ceil division; at
    least one — a live sequence always owns a page)."""
    tokens = max(int(tokens), 1)
    return -(-tokens // int(page_tokens))


class PagePool(object):
    """Preallocated per-model K/V page pool + host-side allocator.

    Device arrays (``k_pages``/``v_pages``) are owned by the engine loop
    (they are donated through the jitted steps and replaced each call);
    this object owns the *accounting*: which page ids are free, which
    are live, high-water marks. Thread-safe — ``submit`` threads consult
    feasibility while the engine thread allocates.
    """

    def __init__(self, num_pages, page_tokens, num_layers, num_heads,
                 head_dim, dtype="float32"):
        if num_pages < 1:
            raise ValueError("num_pages must be >= 1")
        if page_tokens < 1:
            raise ValueError("page_tokens must be >= 1")
        self.num_pages = int(num_pages)
        self.page_tokens = int(page_tokens)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)
        self._lock = _locks.make_lock("serving.kvcache.pool")
        # free list kept SORTED so allocation order is deterministic
        # (tests and replays see the same page ids for the same history)
        self._free = list(range(self.num_pages))
        self._live = set()
        self._refs = {}            # live page id -> refcount (>= 1)
        self._max_live = 0
        self._reclaim = None       # see set_reclaimer
        # rolling log of (monotonic t, pages physically released) — the
        # observed page-release rate that prices a 429 Retry-After hint
        self._release_log = collections.deque(maxlen=256)

    # -- device arrays -------------------------------------------------------
    @property
    def trash_page(self):
        """Id of the write-sink page (the extra last page)."""
        return self.num_pages

    def zeros(self):
        """Freshly zeroed (k_pages, v_pages) device arrays in the pool
        layout — built once by the engine, then donated step to step."""
        import jax.numpy as jnp
        shape = (self.num_layers, self.num_pages + 1, self.page_tokens,
                 self.num_heads, self.head_dim)
        return jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype)

    # -- allocator -----------------------------------------------------------
    def set_reclaimer(self, fn):
        """Install (or clear, with None) the allocation-pressure hook:
        ``fn(n_short) -> pages_freed`` is called OUTSIDE the pool lock
        when ``alloc`` comes up ``n_short`` pages short, and should
        release cold cached pages back (via the normal :meth:`free`
        path). The prefix cache's LRU registers here so warm-but-unused
        prefix pages yield to live traffic before exhaustion fires."""
        with self._lock:
            self._reclaim = fn

    def alloc(self, n):
        """Take ``n`` pages at refcount 1; raises :class:`PoolExhausted`
        (allocating nothing) when fewer are free — after giving the
        registered reclaimer one chance to evict cold cached pages."""
        n = int(n)
        for attempt in (0, 1):
            with self._lock:
                if n <= len(self._free):
                    pages = self._free[:n]
                    del self._free[:n]
                    self._live.update(pages)
                    for p in pages:
                        self._refs[p] = 1
                    self._max_live = max(self._max_live, len(self._live))
                    return pages
                short = n - len(self._free)
                reclaim = self._reclaim
            if attempt or reclaim is None:
                break
            # outside the lock: the reclaimer frees through the normal
            # free() path (which re-takes it) — same lock order as any
            # other holder, no inversion
            if not reclaim(short):
                break
        raise PoolExhausted(
            "kv page pool exhausted: want %d page(s), %d of %d "
            "free" % (n, self.available, self.num_pages))

    def ref(self, pages):
        """Pin additional references on already-live pages (a second
        BlockTable sharing a prefix, or the prefix cache itself).
        Foreign/free ids raise — pinning a page nobody owns would
        resurrect garbage as shared state."""
        pages = list(pages)
        with self._lock:
            bad = [p for p in pages if p not in self._live]
            if bad:
                raise ValueError("ref on pages %s that are not live "
                                 "(free or foreign id)" % bad)
            for p in pages:
                self._refs[p] += 1

    def refcount(self, page):
        """Current refcount of ``page`` (0 when free/foreign)."""
        with self._lock:
            return self._refs.get(page, 0)

    def is_shared(self, page):
        """True when more than one holder pins ``page`` — the engine's
        copy-on-write test before a divergent write."""
        with self._lock:
            return self._refs.get(page, 0) > 1

    def free(self, pages):
        """Drop one reference per page; a page returns to the free list
        only when its refcount reaches zero. Double-free and foreign ids
        raise — including a duplicate id WITHIN one call (one HOLDER
        never legitimately frees the same page twice in one release;
        counting it twice would silently eat another holder's
        reference) — aliasing a live page corrupts another sequence's
        cache, so the accounting must be loud, not forgiving."""
        pages = list(pages)
        with self._lock:
            seen = set()
            bad = []
            for p in pages:
                if p not in self._live or p in seen:
                    bad.append(p)
                seen.add(p)
            if bad:
                raise ValueError("freeing pages %s that are not live "
                                 "(double free, duplicate, or foreign "
                                 "id)" % bad)
            released = 0
            for p in pages:
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    self._live.discard(p)
                    self._free.append(p)
                    released += 1
            if released:
                self._free.sort()
                self._release_log.append((time.monotonic(), released))

    def release_rate(self, window_s=30.0):
        """Observed physical page-release rate (pages/s) over the last
        ``window_s`` seconds — what a 429's Retry-After hint divides
        by: 'you want W pages; at R pages/s that is W/R seconds'."""
        cutoff = time.monotonic() - float(window_s)
        with self._lock:
            events = [(t, n) for t, n in self._release_log if t >= cutoff]
        if not events:
            return 0.0
        span = max(time.monotonic() - events[0][0], 1e-3)
        return sum(n for _, n in events) / span

    # -- accounting ----------------------------------------------------------
    @property
    def available(self):
        with self._lock:
            return len(self._free)

    @property
    def live(self):
        with self._lock:
            return len(self._live)

    def can_fit(self, tokens):
        """Whether a sequence of ``tokens`` total positions could EVER be
        held (feasibility — submit-time shed test)."""
        return pages_for(tokens, self.page_tokens) <= self.num_pages

    @property
    def effective(self):
        """Sum of refcounts — pages this traffic would hold WITHOUT
        sharing. ``effective / live`` is the dedup ratio."""
        with self._lock:
            return sum(self._refs.values())

    def utilization(self):
        """{live, free, num_pages, max_live, frac, effective,
        shared_pages, dedup_ratio} snapshot — ``frac`` stays PHYSICAL
        (the exhaustion/autoscale signal); ``effective`` and
        ``dedup_ratio`` are the sharing win."""
        with self._lock:
            live = len(self._live)
            effective = sum(self._refs.values())
            shared = sum(1 for c in self._refs.values() if c > 1)
            return {"live": live, "free": len(self._free),
                    "num_pages": self.num_pages, "max_live": self._max_live,
                    "frac": live / float(self.num_pages),
                    "effective": effective, "shared_pages": shared,
                    "dedup_ratio": (effective / float(live)
                                    if live else 1.0)}


class BlockTable(object):
    """One sequence's ordered page list + position bookkeeping."""

    __slots__ = ("pool", "pages", "length")

    def __init__(self, pool, pages=(), length=0):
        self.pool = pool
        self.pages = list(pages)
        self.length = int(length)   # positions written so far

    @property
    def capacity(self):
        return len(self.pages) * self.pool.page_tokens

    def ensure(self, tokens):
        """Grow the table to hold ``tokens`` total positions; allocates
        from the pool (raises :class:`PoolExhausted` allocating
        nothing — the caller decides shed vs preempt)."""
        need = pages_for(tokens, self.pool.page_tokens) - len(self.pages)
        if need > 0:
            self.pages.extend(self.pool.alloc(need))

    def trim(self, tokens):
        """Shrink the table back to the pages ``tokens`` total positions
        need, freeing the tail — the speculative-decoding rollback
        primitive: a verify round grows the table to cover k+1
        optimistic positions, and the pages past the accepted point go
        back to the pool between rounds (cache CONTENTS need no
        rollback — stale writes are masked and re-scattered; only the
        allocator accounting rolls back). Rides :meth:`PagePool.free`,
        so a bookkeeping bug double-freeing a trimmed page stays loud.
        Returns the number of pages freed."""
        keep = pages_for(tokens, self.pool.page_tokens)
        if keep >= len(self.pages):
            return 0
        tail = self.pages[keep:]
        del self.pages[keep:]
        self.pool.free(tail)
        self.length = min(self.length, self.capacity)
        return len(tail)

    def release(self):
        """Free every page back to the pool (idempotent)."""
        if self.pages:
            self.pool.free(self.pages)
            self.pages = []
        self.length = 0

    def as_row(self, max_blocks):
        """Fixed-width int32 row for the device block table, trash-padded."""
        import numpy as np
        row = np.full((max_blocks,), self.pool.trash_page, np.int32)
        n = min(len(self.pages), max_blocks)
        row[:n] = self.pages[:n]
        return row
