"""InferenceService: the in-process serving front end.

Ties the registry, micro-batcher, and admission controller into one
object with a blocking ``infer()`` / non-blocking ``infer_async()`` API
and a metrics surface (``.stats``) on the same pattern as
``Executor.stats`` and the async pipeline's profiler counters: request
and shed counts, batch occupancy, queue wait, and p50/p99 end-to-end
latency, mirrored into ``profiler.serving_counters()`` and the
``serving`` section of the timeline artifact.

The HTTP endpoint (:mod:`~paddle_tpu.serving.httpd`) and the
``paddle_tpu serve`` CLI verb are thin shells over this class — tests
and embedders use it directly.
"""
from __future__ import annotations

import collections
import threading

import numpy as np

from .admission import AdmissionController, OverloadError
from .batcher import MicroBatcher, Request

__all__ = ["InferenceService"]

# bounded latency reservoirs: long-lived servers must not grow a list
# per request; percentiles over the most recent window are the ones an
# operator acts on anyway
_WINDOW = 4096


def _percentile(values, q):
    if not values:
        return 0.0
    s = sorted(values)
    return s[min(int(q * len(s)), len(s) - 1)]


class InferenceService(object):
    """Online inference over registered compiled artifacts.

    Usage::

        svc = InferenceService()                       # knobs from FLAGS
        svc.load_model("resnet", "./artifact_dir")     # warm-up included
        outs = svc.infer("resnet", {"x": batch})       # list per fetch
        svc.reload_model("resnet", "./artifact_v2")    # atomic hot swap
        svc.stats                                      # metrics snapshot
        svc.close()

    Knob defaults come from ``FLAGS.serve_max_batch`` /
    ``serve_batch_timeout_ms`` / ``serve_queue_depth``.
    """

    def __init__(self, registry=None, max_batch=None, batch_timeout_ms=None,
                 queue_depth=None):
        from ..flags import FLAGS
        self.max_batch = int(max_batch if max_batch is not None
                             else FLAGS.serve_max_batch)
        self.batch_timeout_ms = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else FLAGS.serve_batch_timeout_ms)
        depth = int(queue_depth if queue_depth is not None
                    else FLAGS.serve_queue_depth)
        from .batcher import padding_buckets
        from .registry import ModelRegistry
        self.registry = registry or ModelRegistry(
            warm_buckets=padding_buckets(self.max_batch))
        self.admission = AdmissionController(depth)
        self._lock = threading.Lock()
        self._counts = collections.Counter()
        self._occupancy_sum = 0
        self._max_occupancy = 0
        self._padded_rows = 0
        self._queue_wait_ms = collections.deque(maxlen=_WINDOW)
        self._latency_ms = collections.deque(maxlen=_WINDOW)
        self._batcher = MicroBatcher(
            self.registry, self.max_batch, self.batch_timeout_ms,
            self.admission, on_shed=self._on_shed,
            on_batch=self._on_batch, on_fail=self._on_fail)
        self._closed = False

    # -- model management ----------------------------------------------------
    def load_model(self, name, dirname, warm=True):
        return self.registry.load(name, dirname, warm=warm)

    def reload_model(self, name, dirname, warm=True):
        """Atomic hot reload; on failure the previous version keeps
        serving (rollback) and the error propagates to this caller."""
        return self.registry.load(name, dirname, warm=warm)

    # -- request path --------------------------------------------------------
    def infer_async(self, name, feed, deadline_ms=None):
        """Enqueue one request; returns its :class:`Request` handle
        (``.wait()`` for the rows). Raises :class:`OverloadError`
        immediately when the queue is full. ``feed`` maps each of the
        model's feed names to one request's arrays (the exported
        per-request shape, no extra batch axis)."""
        entry = self.registry.get(name)   # fail fast on unknown models
        feed = self._checked_feed(name, entry.model, feed)
        req = Request(name, feed,
                      self.admission.deadline_from(deadline_ms))
        with self._lock:
            self._counts["requests"] += 1
        try:
            self._batcher.submit(req)
        except OverloadError:
            with self._lock:
                self._counts["shed_overload"] += 1
            from .. import profiler as _prof
            _prof.update_serving_counters(shed_overload=1)
            raise
        return req

    @staticmethod
    def _checked_feed(name, model, feed):
        """Validate one request against the artifact signature BEFORE it
        queues: a malformed feed must fail its own submit, not poison
        every co-batched request at np.stack time. Array-likes are
        checked by attribute only (never np.asarray on a possibly
        device-resident value — that forces a device->host transfer);
        plain lists/scalars are converted to the exported dtype here."""
        spec = model.feed_spec
        out = {}
        for fn, (shape, dtype) in spec.items():
            if fn not in feed:
                raise ValueError(
                    "feed for model %r is missing %r (wants %s)"
                    % (name, fn, sorted(spec)))
            v = feed[fn]
            if not hasattr(v, "shape"):
                v = np.asarray(v, dtype=dtype)
            if tuple(v.shape) != tuple(shape):
                raise ValueError(
                    "feed %r for model %r has shape %s; the artifact was "
                    "exported for %s (one request = one exported feed, "
                    "no extra batch axis)"
                    % (fn, name, tuple(v.shape), tuple(shape)))
            if str(getattr(v, "dtype", dtype)) != dtype:
                raise ValueError(
                    "feed %r for model %r has dtype %s; the artifact was "
                    "exported for %s" % (fn, name, v.dtype, dtype))
            out[fn] = v
        return out

    def infer(self, name, feed, deadline_ms=None, timeout=None):
        """Blocking inference: list of per-fetch arrays, bit-identical
        to ``CompiledModel.run(feed)`` on the served version."""
        return self.infer_async(name, feed, deadline_ms).wait(timeout)

    # -- observer hooks (dispatch thread) ------------------------------------
    def _on_batch(self, requests, bucket):
        n = len(requests)
        with self._lock:
            self._counts["completed"] += n
            self._counts["batches"] += 1
            self._occupancy_sum += n
            self._max_occupancy = max(self._max_occupancy, n)
            self._padded_rows += bucket - n
            for r in requests:
                self._queue_wait_ms.append(r.queue_wait_ms)
                self._latency_ms.append(r.latency_ms)
        from .. import profiler as _prof
        _prof.update_serving_counters(
            requests=n, batches=1, padded_rows=bucket - n,
            max_occupancy=n,
            queue_wait_ms=sum(r.queue_wait_ms for r in requests))

    def _on_shed(self, request, reason):
        with self._lock:
            self._counts["shed_" + reason] += 1
        from .. import profiler as _prof
        _prof.update_serving_counters(**{"shed_" + reason: 1})

    def _on_fail(self, requests, exc):
        with self._lock:
            self._counts["failed"] += len(requests)
        from .. import profiler as _prof
        _prof.update_serving_counters(failed=len(requests))

    # -- metrics -------------------------------------------------------------
    @property
    def stats(self):
        """Snapshot: counts, occupancy, queue wait, p50/p99 latency."""
        with self._lock:
            c = dict(self._counts)
            batches = c.get("batches", 0)
            qw = list(self._queue_wait_ms)
            lat = list(self._latency_ms)
            snap = {
                "requests": c.get("requests", 0),
                "completed": c.get("completed", 0),
                "failed": c.get("failed", 0),
                "shed_overload": c.get("shed_overload", 0),
                "shed_deadline": c.get("shed_deadline", 0),
                "pending": self._batcher.pending(),
                "batches": batches,
                "batch_occupancy": (self._occupancy_sum / batches
                                    if batches else 0.0),
                "max_occupancy": self._max_occupancy,
                "padded_rows": self._padded_rows,
                "queue_wait_ms_p50": _percentile(qw, 0.50),
                "queue_wait_ms_p99": _percentile(qw, 0.99),
                "latency_ms_p50": _percentile(lat, 0.50),
                "latency_ms_p99": _percentile(lat, 0.99),
                "models": self.registry.versions(),
            }
        snap["shed"] = snap["shed_overload"] + snap["shed_deadline"]
        return snap

    # -- lifecycle -----------------------------------------------------------
    def close(self):
        if not self._closed:
            self._closed = True
            self._batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # convenience for embedders comparing against the offline path
    @staticmethod
    def as_numpy(rows):
        return [np.asarray(r) for r in rows]
