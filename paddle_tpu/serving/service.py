"""InferenceService: the in-process serving front end.

Ties the registry, micro-batcher, and admission controller into one
object with a blocking ``infer()`` / non-blocking ``infer_async()`` API
and a metrics surface (``.stats``) on the same pattern as
``Executor.stats`` and the async pipeline's profiler counters: request
and shed counts, batch occupancy, queue wait, and p50/p99 end-to-end
latency, mirrored into ``profiler.serving_counters()`` and the
``serving`` section of the timeline artifact.

Two request families share the front end:

- **one-shot inference** (``infer`` / ``infer_async``) over compiled
  artifacts through the micro-batcher — the PR-4 path;
- **autoregressive generation** (``generate`` / ``generate_async``)
  over generative artifacts through a per-model
  :class:`~paddle_tpu.serving.generator.GenerationEngine` (continuous
  batching + paged KV-cache). ``load_model`` auto-detects which kind a
  directory holds; eligibility is decided per artifact, and the
  micro-batcher keeps serving the non-autoregressive models.

The HTTP endpoint (:mod:`~paddle_tpu.serving.httpd`) and the
``paddle_tpu serve`` CLI verb are thin shells over this class — tests
and embedders use it directly.
"""
from __future__ import annotations

import collections
import threading

import numpy as np

from .admission import (AdmissionController, ModelUnavailableError,
                        OverloadError, ServingError)
from .batcher import MicroBatcher, Request
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["InferenceService", "GenEntry"]

# bounded latency reservoirs: long-lived servers must not grow a list
# per request; percentiles over the most recent window are the ones an
# operator acts on anyway
_WINDOW = 4096


def _percentile(values, q):
    if not values:
        return 0.0
    s = sorted(values)
    return s[min(int(q * len(s)), len(s) - 1)]


class GenEntry(object):
    """One published generative (name, version): the registry
    ModelEntry's shape, generation-flavored. ``engine_kwargs`` records
    the deployment's engine knobs so a later reload without explicit
    kwargs (the HTTP ``:reload`` path) rebuilds the SAME geometry
    instead of silently falling back to the flag defaults."""

    __slots__ = ("name", "version", "dirname", "engine", "engine_kwargs",
                 "loaded_at")

    def __init__(self, name, version, dirname, engine, engine_kwargs=None):
        import time as _time
        self.name = name
        self.version = version
        self.dirname = dirname
        self.engine = engine
        self.engine_kwargs = dict(engine_kwargs or {})
        self.loaded_at = _time.time()

    @property
    def warmup_ms(self):
        return self.engine.warmup_ms

    def describe(self):
        eng = self.engine
        return {"version": self.version, "dirname": self.dirname,
                "loaded_at": self.loaded_at, "kind": "generative",
                "warmup_ms": round(eng.warmup_ms, 3),
                "max_running": eng.max_running,
                "kv_pages": eng.pool.num_pages,
                "page_tokens": eng.pool.page_tokens,
                "max_context": eng.max_context}


class InferenceService(object):
    """Online inference over registered compiled artifacts.

    Usage::

        svc = InferenceService()                       # knobs from FLAGS
        svc.load_model("resnet", "./artifact_dir")     # warm-up included
        outs = svc.infer("resnet", {"x": batch})       # list per fetch
        svc.reload_model("resnet", "./artifact_v2")    # atomic hot swap
        svc.stats                                      # metrics snapshot
        svc.close()

    Knob defaults come from ``FLAGS.serve_max_batch`` /
    ``serve_batch_timeout_ms`` / ``serve_queue_depth``.
    """

    def __init__(self, registry=None, max_batch=None, batch_timeout_ms=None,
                 queue_depth=None, tier=None):
        from ..flags import FLAGS
        # serving tier class for the disaggregated fleet (FLAGS.
        # serve_tier): "" = do-everything replica, "prefill"/"decode"
        # advertise the class through /statz and /healthz so the router
        # never dispatches a tier to work outside its class. The tier
        # is a ROUTING contract, not a capability fence — a prefill
        # replica can still decode (the re-prefill fallback depends on
        # decode replicas being whole engines).
        self.tier = str(tier if tier is not None else FLAGS.serve_tier)
        if self.tier not in ("", "prefill", "decode"):
            raise ValueError("tier must be '', 'prefill' or 'decode', "
                             "got %r" % self.tier)
        self.max_batch = int(max_batch if max_batch is not None
                             else FLAGS.serve_max_batch)
        self.batch_timeout_ms = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else FLAGS.serve_batch_timeout_ms)
        depth = int(queue_depth if queue_depth is not None
                    else FLAGS.serve_queue_depth)
        from .batcher import padding_buckets
        from .registry import ModelRegistry
        self.registry = registry or ModelRegistry(
            warm_buckets=padding_buckets(self.max_batch))
        self.admission = AdmissionController(depth)
        self._lock = _locks.make_lock("serving.service.state")
        self._counts = collections.Counter()
        self._occupancy_sum = 0
        self._max_occupancy = 0
        self._padded_rows = 0
        self._queue_wait_ms = collections.deque(maxlen=_WINDOW)
        self._latency_ms = collections.deque(maxlen=_WINDOW)
        self._batcher = MicroBatcher(
            self.registry, self.max_batch, self.batch_timeout_ms,
            self.admission, on_shed=self._on_shed,
            on_batch=self._on_batch, on_fail=self._on_fail)
        self._generators = {}       # name -> GenEntry
        self._gen_versions = {}     # name -> last assigned version int
        # name -> (gen version, disagg.PrefillEngine): the prefill-tier
        # face over the SAME model a generative entry serves, built
        # lazily on the first ``:prefill`` and retired with its entry —
        # version-keyed so a hot reload never exports KV computed by
        # the previous weights
        self._prefill_engines = {}
        # serializes generative load/reload/drop per SERVICE: two racing
        # :reload threads would otherwise both build engines and both
        # retire only the older one — the loser's engine thread and
        # device-resident pool would leak for the process lifetime
        self._gen_reload_lock = _locks.make_lock("serving.service.gen_reload")
        self._closed = False

    # -- model management ----------------------------------------------------
    def load_model(self, name, dirname, warm=True, **gen_kwargs):
        """Load (or hot-reload) ``dirname`` as ``name``. The artifact
        kind decides the path: an ``export_generative`` directory builds
        a generation engine (``gen_kwargs`` — max_running/kv_pages/...
        — apply there); anything else goes through the compiled-model
        registry (``gen_kwargs`` are rejected: a compiled artifact has
        no engine to configure)."""
        from ..inference import is_generative_artifact
        if is_generative_artifact(dirname):
            return self.load_generative(name, dirname, warm=warm,
                                        **gen_kwargs)
        if gen_kwargs:
            raise TypeError(
                "%r is a compiled artifact; generation engine knobs %s "
                "do not apply" % (dirname, sorted(gen_kwargs)))
        entry = self.registry.load(name, dirname, warm=warm)
        # a compiled artifact replacing a generative name: retire the
        # stale engine, or it would keep answering :generate with the
        # previous model forever
        self._drop_generative(name)
        return entry

    def reload_model(self, name, dirname, warm=True, **gen_kwargs):
        """Atomic hot reload; on failure the previous version keeps
        serving (rollback) and the error propagates to this caller."""
        return self.load_model(name, dirname, warm=warm, **gen_kwargs)

    # cap on how long a hot reload waits for the previous engine's
    # in-flight generations before closing it anyway
    _DRAIN_TIMEOUT_S = 60.0

    def load_generative(self, name, dirname, warm=True, **engine_kwargs):
        """Load a generative artifact and stand its engine up. The new
        engine is fully built (and warmed) BEFORE the publish swap; the
        previous engine drains its in-flight sequences (new submits go
        to the replacement) and closes after the swap — the registry's
        hot-reload discipline. A reload without explicit
        ``engine_kwargs`` reuses the previous deployment's knobs (the
        HTTP ``:reload`` path must not silently reset the pool
        geometry to flag defaults). On failure the previous version
        keeps serving with a recorded ``reload_rollback`` event.

        A speculative pairing (``inference.export_speculative``) is
        auto-detected: the draft model and the pairing's k ride into
        the engine kwargs, and the ARTIFACT is the source of truth —
        it overrides a stale draft reused from the previous
        deployment's kwargs, and reloading a plain artifact over a
        speculative one drops the old draft rather than resurrecting
        it."""
        from ..inference import (is_speculative_artifact,
                                 load_generative, load_speculative)
        from ..resilience import record_event
        from .generator import GenerationEngine
        with self._gen_reload_lock:
            self._check_open()
            prev = self._generators.get(name)
            explicit_draft = "draft_model" in engine_kwargs
            if not engine_kwargs and prev is not None:
                engine_kwargs = dict(prev.engine_kwargs)
            engine_kwargs.setdefault("queue_depth",
                                     self.admission.queue_depth)
            try:
                if is_speculative_artifact(dirname):
                    model, draft, spec_k = load_speculative(dirname)
                    if not explicit_draft:
                        engine_kwargs["draft_model"] = draft
                        # an explicitly-passed spec_k (CLI --spec_k)
                        # still wins over the pairing's qualified k
                        engine_kwargs.setdefault("spec_k", spec_k)
                elif not explicit_draft:
                    # plain artifact: never inherit a previous
                    # deployment's draft across the reload
                    model = load_generative(dirname)
                    engine_kwargs.pop("draft_model", None)
                    engine_kwargs.pop("spec_k", None)
                else:
                    model = load_generative(dirname)
                engine = GenerationEngine(model, name=name, warm=warm,
                                          **engine_kwargs)
            except BaseException as e:
                if prev is not None:
                    record_event("reload_rollback", site="serving.reload",
                                 model=name, kept_version=prev.version,
                                 dirname=dirname, error=repr(e))
                raise
            with self._lock:
                version = self._gen_versions.get(name, 0) + 1
                self._gen_versions[name] = version
                entry = GenEntry(name, version, dirname, engine,
                                 engine_kwargs)
                self._generators[name] = entry
            record_event("model_loaded", site="serving.reload", model=name,
                         version=version, dirname=dirname,
                         artifact="generative",
                         warmup_ms=round(engine.warmup_ms, 3))
            if prev is not None:
                prev.engine.drain(timeout=self._DRAIN_TIMEOUT_S)
                prev.engine.close()
            self._drop_prefill(name, keep_version=version)
            # a generative artifact replacing a compiled name: retire the
            # stale compiled entry, or it would keep answering :predict
            # with the previous model forever
            self.registry.unload(name)
            return entry

    def register_generative(self, name, model, **engine_kwargs):
        """In-process entry point (tests/benchmarks/embedders): stand an
        engine up over an already-built
        :class:`~paddle_tpu.models.transformer.TransformerLM`."""
        from .generator import GenerationEngine
        with self._gen_reload_lock:
            self._check_open()
            prev = self._generators.get(name)
            engine_kwargs.setdefault("queue_depth",
                                     self.admission.queue_depth)
            engine = GenerationEngine(model, name=name, **engine_kwargs)
            with self._lock:
                version = self._gen_versions.get(name, 0) + 1
                self._gen_versions[name] = version
                entry = GenEntry(name, version, "<in-process>", engine,
                                 engine_kwargs)
                self._generators[name] = entry
            if prev is not None:
                prev.engine.drain(timeout=self._DRAIN_TIMEOUT_S)
                prev.engine.close()
            self._drop_prefill(name, keep_version=version)
            self.registry.unload(name)
            return entry

    def _check_open(self):
        """Called under ``_gen_reload_lock``: a generative load racing
        :meth:`close` must lose — an engine published after the close
        sweep would leak its thread and device-resident page pool for
        the process lifetime."""
        if self._closed:
            raise RuntimeError("InferenceService is closed")

    def _drop_generative(self, name):
        """Retire ``name``'s generation engine (cross-kind replacement),
        draining in-flight work first."""
        with self._gen_reload_lock:
            with self._lock:
                entry = self._generators.pop(name, None)
            if entry is not None:
                entry.engine.drain(timeout=self._DRAIN_TIMEOUT_S)
                entry.engine.close()
            self._drop_prefill(name)

    def _drop_prefill(self, name, keep_version=None):
        """Retire ``name``'s cached prefill engine unless it already
        matches ``keep_version`` — called on reload/drop so a stale
        prefill face never outlives the weights it was traced over."""
        with self._lock:
            cached = self._prefill_engines.get(name)
            if cached is None or cached[0] == keep_version:
                return
            del self._prefill_engines[name]
        cached[1].close()

    def _gen_entry(self, name):
        with self._lock:
            entry = self._generators.get(name)
            known = sorted(self._generators) if entry is None else None
        if entry is None:
            raise ModelUnavailableError(
                "no generative model registered under %r (registered: "
                "%s)" % (name, known or "none"))
        return entry

    def model_info(self):
        """Registry listing covering both families (httpd /v1/models)."""
        info = self.registry.info()
        with self._lock:
            gens = dict(self._generators)
        info.update({n: e.describe() for n, e in gens.items()})
        return info

    def readiness(self):
        """Per-model readiness detail for ``/healthz``: what a router
        needs to weight and drain on — kind, version, queue depth, and
        (generative) KV page utilization + draining state. Presence of
        a model key means "loaded"; ``draining`` True means the engine
        is handing over to a replacement and new work should go
        elsewhere."""
        out = {}
        for name in self.registry.names():
            try:
                entry = self.registry.get(name)
            except ModelUnavailableError:
                continue
            out[name] = {"kind": "compiled", "version": entry.version,
                         "queued": self._batcher.pending_for(name),
                         "draining": False}
        with self._lock:
            gens = dict(self._generators)
        for name, e in gens.items():
            st = e.engine.stats
            out[name] = {"kind": "generative", "version": e.version,
                         "queued": st["queued"], "running": st["running"],
                         "page_utilization": round(
                             st["page_utilization"]["frac"], 4),
                         "draining": e.engine.draining}
        return out

    def retry_after_ms(self, model=None):
        """Back-off hint for 429/503 answers, derived from the queue-wait
        the service is CURRENTLY delivering: a client that retries after
        roughly one p99 queue-wait arrives behind a drained backlog
        instead of re-feeding the convoy. Floor: one batch-formation
        window. For a generative ``model``, the inter-token p50 times
        the queued depth estimates the engine's drain time and takes
        the max. A pool-exhausted shed takes a further max against the
        OBSERVED page-release rate: queued-depth-many sequences each
        need pages, and pages come back at ``pool.release_rate()``
        pages/s, so waiting ``(queued+1)/rate`` seconds is when capacity
        plausibly exists — the batch window would tell an exhausted-pool
        client to hammer a server that cannot admit anyone. Clamped to
        [1 ms, 30 s]."""
        with self._lock:
            qw = list(self._queue_wait_ms)
            gen = self._generators.get(model) if model else None
        est = max(self.batch_timeout_ms, _percentile(qw, 0.99))
        if gen is not None:
            st = gen.engine.stats
            est = max(est,
                      st["intertoken_ms_p50"] * (st["queued"] + 1))
            rate = st.get("page_release_rate", 0.0)
            if rate > 0.0:
                est = max(est, 1000.0 * (st["queued"] + 1) / rate)
        return min(max(est, 1.0), 30000.0)

    # -- request path --------------------------------------------------------
    def infer_async(self, name, feed, deadline_ms=None):
        """Enqueue one request; returns its :class:`Request` handle
        (``.wait()`` for the rows). Raises :class:`OverloadError`
        immediately when the queue is full. ``feed`` maps each of the
        model's feed names to one request's arrays (the exported
        per-request shape, no extra batch axis)."""
        entry = self.registry.get(name)   # fail fast on unknown models
        feed = self._checked_feed(name, entry.model, feed)
        req = Request(name, feed,
                      self.admission.deadline_from(deadline_ms))
        with self._lock:
            self._counts["requests"] += 1
        try:
            self._batcher.submit(req)
        except OverloadError:
            with self._lock:
                self._counts["shed_overload"] += 1
            from .. import profiler as _prof
            _prof.update_serving_counters(shed_overload=1)
            raise
        return req

    @staticmethod
    def _checked_feed(name, model, feed):
        """Validate one request against the artifact signature BEFORE it
        queues: a malformed feed must fail its own submit, not poison
        every co-batched request at np.stack time. Array-likes are
        checked by attribute only (never np.asarray on a possibly
        device-resident value — that forces a device->host transfer);
        plain lists/scalars are converted to the exported dtype here."""
        spec = model.feed_spec
        out = {}
        for fn, (shape, dtype) in spec.items():
            if fn not in feed:
                raise ValueError(
                    "feed for model %r is missing %r (wants %s)"
                    % (name, fn, sorted(spec)))
            v = feed[fn]
            if not hasattr(v, "shape"):
                v = np.asarray(v, dtype=dtype)
            if tuple(v.shape) != tuple(shape):
                raise ValueError(
                    "feed %r for model %r has shape %s; the artifact was "
                    "exported for %s (one request = one exported feed, "
                    "no extra batch axis)"
                    % (fn, name, tuple(v.shape), tuple(shape)))
            if str(getattr(v, "dtype", dtype)) != dtype:
                raise ValueError(
                    "feed %r for model %r has dtype %s; the artifact was "
                    "exported for %s" % (fn, name, v.dtype, dtype))
            out[fn] = v
        return out

    def infer(self, name, feed, deadline_ms=None, timeout=None):
        """Blocking inference: list of per-fetch arrays, bit-identical
        to ``CompiledModel.run(feed)`` on the served version."""
        return self.infer_async(name, feed, deadline_ms).wait(timeout)

    # -- generation path -----------------------------------------------------
    def generate_async(self, name, tokens, max_new_tokens=16,
                       temperature=0.0, seed=0, deadline_ms=None,
                       spec_k=None):
        """Enqueue one autoregressive generation on ``name``'s engine;
        returns its :class:`~paddle_tpu.serving.generator.GenRequest`
        handle (``.wait()`` for the
        :class:`~paddle_tpu.serving.generator.GenResult`). Sheds raise
        immediately (OverloadError / PoolExhausted), the engine's
        submit contract. The handle's ``model_version`` is stamped from
        the entry that took the submit, so responses attribute tokens
        to the version that produced them even across a hot reload."""
        entry = self._gen_entry(name)
        try:
            req = entry.engine.submit(
                tokens, max_new_tokens=max_new_tokens,
                temperature=temperature, seed=seed,
                deadline_ms=deadline_ms, spec_k=spec_k)
        except ServingError:
            # lost the race with a hot reload: the entry fetched above
            # drained/closed before this submit landed. Retry ONCE
            # against the current registry state — the replacement
            # engine owns new traffic; a second loss means the model is
            # genuinely going away and the error is real
            entry = self._gen_entry(name)
            req = entry.engine.submit(
                tokens, max_new_tokens=max_new_tokens,
                temperature=temperature, seed=seed,
                deadline_ms=deadline_ms, spec_k=spec_k)
        req.model_version = entry.version
        return req

    # -- disaggregated tier path ---------------------------------------------
    def _prefill_for(self, entry):
        """The cached prefill engine for ``entry``, built on first use
        over the entry's OWN model object (same weights, same page
        geometry as the decode pools it will hand off to)."""
        with self._lock:
            cached = self._prefill_engines.get(entry.name)
            if cached is not None and cached[0] == entry.version:
                return cached[1]
        from .disagg import PrefillEngine
        eng = PrefillEngine(entry.engine.model,
                            page_tokens=entry.engine.pool.page_tokens,
                            name=entry.name, eos_id=entry.engine.eos_id)
        with self._lock:
            cached = self._prefill_engines.get(entry.name)
            if cached is not None and cached[0] == entry.version:
                stale = eng          # lost a build race: keep the winner
                eng = cached[1]
            else:
                stale = cached[1] if cached is not None else None
                self._prefill_engines[entry.name] = (entry.version, eng)
        if stale is not None:
            stale.close()
        return eng

    def prefill(self, name, tokens, max_new_tokens=16, temperature=0.0,
                seed=0):
        """Prefill-tier entry point (httpd ``:prefill``): run ONLY the
        prompt pass on ``name``'s weights and return the
        :class:`~paddle_tpu.serving.disagg.HandoffArtifact` — finished
        KV pages + enough request state for any decode-class replica to
        continue bit-exactly."""
        entry = self._gen_entry(name)
        return self._prefill_for(entry).prefill(
            tokens, max_new_tokens=max_new_tokens,
            temperature=temperature, seed=seed)

    def decode_handoff_async(self, name, payload, deadline_ms=None):
        """Decode-tier entry point (httpd ``:decode``): install a
        shipped artifact (wire payload or HandoffArtifact) into
        ``name``'s engine via :func:`~paddle_tpu.serving.disagg.ship`
        and return the request handle. The ship fallback applies — a
        bad artifact re-prefills HERE rather than failing the request —
        while overload/pool-exhaustion propagate as backpressure."""
        from .disagg import HandoffArtifact, ship
        artifact = (payload if isinstance(payload, HandoffArtifact)
                    else HandoffArtifact.from_payload(payload))
        entry = self._gen_entry(name)
        try:
            req = ship(artifact, entry.engine, deadline_ms=deadline_ms)
        except ServingError:
            # same reload race as generate_async: retry once against
            # the current entry
            entry = self._gen_entry(name)
            req = ship(artifact, entry.engine, deadline_ms=deadline_ms)
        req.model_version = entry.version
        return req

    def decode_handoff(self, name, payload, deadline_ms=None, timeout=None):
        """Blocking :meth:`decode_handoff_async` -> GenResult."""
        return self.decode_handoff_async(name, payload,
                                         deadline_ms=deadline_ms).wait(timeout)

    def generate(self, name, tokens, max_new_tokens=16, temperature=0.0,
                 seed=0, deadline_ms=None, timeout=None, spec_k=None):
        """Blocking generation -> GenResult (greedy outputs are
        token-identical to sequential full-sequence decode of the same
        prompt — the continuous-batching parity contract)."""
        return self.generate_async(name, tokens, max_new_tokens,
                                   temperature, seed, deadline_ms,
                                   spec_k=spec_k).wait(timeout)

    # -- observer hooks (dispatch thread) ------------------------------------
    def _on_batch(self, requests, bucket):
        n = len(requests)
        with self._lock:
            self._counts["completed"] += n
            self._counts["batches"] += 1
            self._occupancy_sum += n
            self._max_occupancy = max(self._max_occupancy, n)
            self._padded_rows += bucket - n
            for r in requests:
                self._queue_wait_ms.append(r.queue_wait_ms)
                self._latency_ms.append(r.latency_ms)
        from .. import profiler as _prof
        _prof.update_serving_counters(
            requests=n, batches=1, padded_rows=bucket - n,
            max_occupancy=n,
            queue_wait_ms=sum(r.queue_wait_ms for r in requests))

    def _on_shed(self, request, reason):
        with self._lock:
            self._counts["shed_" + reason] += 1
        from .. import profiler as _prof
        _prof.update_serving_counters(**{"shed_" + reason: 1})

    def _on_fail(self, requests, exc):
        with self._lock:
            self._counts["failed"] += len(requests)
        from .. import profiler as _prof
        _prof.update_serving_counters(failed=len(requests))

    # -- metrics -------------------------------------------------------------
    @property
    def stats(self):
        """Snapshot: counts, occupancy, queue wait, p50/p99 latency."""
        with self._lock:
            c = dict(self._counts)
            batches = c.get("batches", 0)
            qw = list(self._queue_wait_ms)
            lat = list(self._latency_ms)
            snap = {
                "requests": c.get("requests", 0),
                "completed": c.get("completed", 0),
                "failed": c.get("failed", 0),
                "shed_overload": c.get("shed_overload", 0),
                "shed_deadline": c.get("shed_deadline", 0),
                "pending": self._batcher.pending(),
                "max_batch": self.max_batch,
                "batches": batches,
                "batch_occupancy": (self._occupancy_sum / batches
                                    if batches else 0.0),
                "max_occupancy": self._max_occupancy,
                "padded_rows": self._padded_rows,
                "queue_wait_ms_p50": _percentile(qw, 0.50),
                "queue_wait_ms_p99": _percentile(qw, 0.99),
                "latency_ms_p50": _percentile(lat, 0.50),
                "latency_ms_p99": _percentile(lat, 0.99),
                "models": self.registry.versions(),
                "tier": self.tier,
            }
            gens = dict(self._generators)
            pre = {n: v[1] for n, v in self._prefill_engines.items()}
        snap["shed"] = snap["shed_overload"] + snap["shed_deadline"]
        if gens:
            snap["generation"] = {n: e.engine.stats
                                  for n, e in sorted(gens.items())}
            snap["models"].update({n: e.version
                                   for n, e in gens.items()})
        if pre:
            snap["prefill"] = {n: e.stats for n, e in sorted(pre.items())}
        return snap

    # -- lifecycle -----------------------------------------------------------
    def close(self):
        # _closed flips under _gen_reload_lock so an in-flight
        # load_generative either publishes BEFORE the sweep below
        # (its engine is collected here) or observes _closed and
        # refuses — no engine can be published into a closed service
        with self._gen_reload_lock:
            if self._closed:
                return
            self._closed = True
            with self._lock:
                gens = list(self._generators.values())
                self._generators.clear()
                pre = [v[1] for v in self._prefill_engines.values()]
                self._prefill_engines.clear()
        for p in pre:
            p.close()
        self._batcher.close()
        # same contract as hot reload: in-flight generations finish
        # (bounded) before the engine is torn down, so a SIGTERM
        # drain-and-exit never 500s a request mid-stream
        for e in gens:
            e.engine.drain(timeout=self._DRAIN_TIMEOUT_S)
            e.engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # convenience for embedders comparing against the offline path
    @staticmethod
    def as_numpy(rows):
        return [np.asarray(r) for r in rows]
