"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

A TPU (and XLA generally) amortizes dispatch overhead over batch size;
serving traffic arrives one request at a time. The micro-batcher bridges
the two: requests enter a bounded queue, and a single dispatch thread
forms batches per **(model, feed-shape signature)** — it takes the
oldest pending request, then waits up to ``batch_timeout_ms`` (the
latency/throughput knob) for more same-model same-shape requests before
stacking up to ``max_batch`` of them and driving ONE
``CompiledModel.run_many`` device dispatch. Results are scattered back
to the per-request futures. Shape-bucket routing means mixed-shape
traffic to one model (e.g. per-shape artifact variants sharing a name,
or a direct embedder whose model runs several shapes) coalesces into
per-shape full batches instead of poisoning the stack — a batch is
shape-homogeneous by construction.

Two compile-stability rules keep the hot path trace-free:

- **fixed padding buckets**: a batch of R requests is padded (by
  repeating the last request's rows) up to the smallest bucket in
  ``padding_buckets(max_batch)`` — powers of two capped by max_batch —
  so ``run_many``'s ``lax.scan`` sees only ``len(buckets)`` distinct
  stack depths, never one per queue depth. Padded rows are computed and
  discarded; scan iterations are independent, so live rows stay
  bit-identical to per-request ``run()``.
- **singleton fast path**: a batch of one skips the scan entirely and
  calls ``run()`` — same compiled program the warm-up primed.

Failure contract: the dispatch edge is fault site ``serving.dispatch``;
a raise there fails that batch's requests (each future carries the
error) and records a ``batch_failed`` degradation event — the dispatch
loop itself never dies. Expired requests are shed at dispatch via the
:class:`~paddle_tpu.serving.admission.AdmissionController`.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

from ..resilience import fault_point, record_event
from .admission import ModelUnavailableError, ServingError
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["padding_buckets", "bucket_for", "feed_shape_sig", "Request",
           "MicroBatcher"]


def padding_buckets(max_batch):
    """Fixed stack-depth buckets for ``max_batch``: powers of two, with
    ``max_batch`` itself as the cap (e.g. 8 -> [1, 2, 4, 8];
    6 -> [1, 2, 4, 6]). Each bucket is one ``lax.scan`` trace."""
    max_batch = max(int(max_batch), 1)
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def bucket_for(r, buckets):
    """Smallest bucket that fits ``r`` requests."""
    for b in buckets:
        if b >= r:
            return b
    return buckets[-1]


def feed_shape_sig(feed):
    """Canonical (name, shape) signature of one request's feed — the
    shape-bucket routing key. Attribute-only on array-likes (never
    np.asarray a possibly device-resident value); plain lists fall back
    to np.shape."""
    sig = []
    for fn in sorted(feed):
        v = feed[fn]
        shape = getattr(v, "shape", None)
        if shape is None:
            shape = np.shape(v)
        sig.append((fn, tuple(int(d) for d in shape)))
    return tuple(sig)


class Request(object):
    """One queued inference request; resolves to a list of per-fetch
    arrays (no leading batch axis added or removed — the rows are
    exactly what ``run()`` would have returned)."""

    __slots__ = ("model", "feed", "shape_sig", "deadline_t", "enqueue_t",
                 "dequeue_t", "done_t", "_done", "_result", "_error")

    def __init__(self, model, feed, deadline_t=None):
        self.model = model
        self.feed = feed
        self.shape_sig = feed_shape_sig(feed)
        self.deadline_t = deadline_t
        self.enqueue_t = time.monotonic()
        self.dequeue_t = None
        self.done_t = None
        self._done = threading.Event()
        self._result = None
        self._error = None

    def resolve(self, result):
        self._result = result
        self.done_t = time.monotonic()
        self._done.set()

    def fail(self, exc):
        self._error = exc
        self.done_t = time.monotonic()
        self._done.set()

    @property
    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block for the result; re-raises the shed/dispatch error."""
        if not self._done.wait(timeout):
            raise TimeoutError("inference request still pending after "
                               "%.3fs (model %r)" % (timeout, self.model))
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def queue_wait_ms(self):
        end = self.dequeue_t or self.done_t or time.monotonic()
        return (end - self.enqueue_t) * 1e3

    @property
    def latency_ms(self):
        end = self.done_t or time.monotonic()
        return (end - self.enqueue_t) * 1e3


class MicroBatcher(object):
    """Bounded per-model request queues + the single dispatch thread.

    ``admission`` bounds the total queued depth (checked under the queue
    lock, so the bound is exact) and sheds expired requests at dispatch.
    ``on_shed(request, reason)`` / ``on_batch(requests, bucket)`` /
    ``on_fail(requests, exc)`` are observer hooks the owning service
    uses for metrics; they run on the dispatch thread and must be cheap.
    """

    def __init__(self, registry, max_batch, batch_timeout_ms, admission,
                 on_shed=None, on_batch=None, on_fail=None):
        self.registry = registry
        self.max_batch = max(int(max_batch), 1)
        self.batch_timeout_s = max(float(batch_timeout_ms), 0.0) / 1e3
        self.buckets = padding_buckets(self.max_batch)
        self.admission = admission
        self._on_shed = on_shed or (lambda req, reason: None)
        self._on_batch = on_batch or (lambda reqs, bucket: None)
        self._on_fail = on_fail or (lambda reqs, exc: None)
        # shape-bucket routing: queues are keyed (model, feed shape
        # signature), so a formed batch is shape-homogeneous BY
        # CONSTRUCTION — mixed-shape traffic to one model coalesces
        # into per-shape full batches instead of poisoning np.stack
        self._queues = {}           # (model, shape_sig) -> deque[Request]
        self._cond = _locks.make_condition("serving.batcher.cond")
        self._running = True
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="paddle_tpu-serve-dispatch",
                                        daemon=True)
        self._thread.start()

    # -- producer side -------------------------------------------------------
    def submit(self, request):
        """Enqueue under the admission bound; raises
        :class:`OverloadError` on a full queue, :class:`ServingError`
        after close()."""
        with self._cond:
            if not self._running:
                raise ServingError("serving dispatch loop is closed")
            self.admission.check_queue(self._pending_locked(),
                                       model=request.model)
            self._queues.setdefault(
                (request.model, request.shape_sig),
                collections.deque()).append(request)
            self._cond.notify_all()
        return request

    def pending(self):
        with self._cond:
            return self._pending_locked()

    def pending_for(self, model):
        """Queued requests for ONE model (summed over its shape-bucket
        queues) — the per-model queue depth the /healthz readiness
        detail reports."""
        with self._cond:
            return sum(len(q) for (m, _sig), q in self._queues.items()
                       if m == model)

    def _pending_locked(self):
        return sum(len(q) for q in self._queues.values())

    # -- dispatch loop -------------------------------------------------------
    def _dispatch_loop(self):
        while True:
            batch = self._form_batch()
            if batch is None:
                return
            (name, _sig), requests = batch
            if requests:
                self._run_batch(name, requests)

    def _form_batch(self):
        """Block for work, then give later arrivals up to
        ``batch_timeout_s`` (measured from the OLDEST queued request) to
        coalesce. Returns ((model, shape_sig), [requests]) or None at
        shutdown."""
        with self._cond:
            while self._running and self._pending_locked() == 0:
                self._cond.wait(0.1)
            if not self._running and self._pending_locked() == 0:
                return None
            # serve the (model, shape) queue whose head has waited
            # longest — later same-shape arrivals coalesce behind it
            key = min((k for k, q in self._queues.items() if q),
                      key=lambda k: self._queues[k][0].enqueue_t)
            q = self._queues[key]
            form_deadline = q[0].enqueue_t + self.batch_timeout_s
            while self._running and len(q) < self.max_batch:
                rem = form_deadline - time.monotonic()
                if rem <= 0:
                    break
                self._cond.wait(rem)
            if not self._running:
                # close() ran while we waited (the wait releases the
                # lock): it already collected and failed these requests
                # as shutdown orphans — popping our stale deque ref
                # would dispatch work whose futures are dead
                return key, []
            now = time.monotonic()
            take = min(len(q), self.max_batch)
            requests = [q.popleft() for _ in range(take)]
            for r in requests:
                r.dequeue_t = now
            if not q:
                del self._queues[key]
            self._cond.notify_all()
        return key, requests

    def _run_batch(self, name, requests):
        # shed what is already dead, then dispatch the rest as one stack
        live = []
        for r in requests:
            if self.admission.expired(r):
                self.admission.shed_deadline(r)
                self._on_shed(r, "deadline")
            else:
                live.append(r)
        if not live:
            return
        try:
            entry = self.registry.get(name)
        except ModelUnavailableError as e:
            for r in live:
                r.fail(e)
            self._on_fail(live, e)
            return
        model = entry.model
        n_live = len(live)
        bucket = bucket_for(n_live, self.buckets)
        try:
            fault_point("serving.dispatch")
            if bucket == 1:
                rows = [[np.asarray(o) for o in model.run(live[0].feed)]]
            else:
                # pad to the bucket by repeating the last live request's
                # rows — computed and discarded, never returned
                pad = [live[-1]] * (bucket - n_live)
                stacked = {
                    fn: np.stack([np.asarray(r.feed[fn])
                                  for r in live + pad])
                    for fn in model.feed_names}
                outs = [np.asarray(o) for o in model.run_many(stacked)]
                rows = [[o[i] for o in outs] for i in range(n_live)]
        except BaseException as e:
            record_event("batch_failed", site="serving.dispatch",
                         model=name, version=entry.version,
                         requests=n_live, error=repr(e))
            for r in live:
                r.fail(e)
            self._on_fail(live, e)
            return
        for r, row in zip(live, rows):
            r.resolve(row)
        self._on_batch(live, bucket)

    # -- shutdown ------------------------------------------------------------
    def close(self):
        """Stop the dispatch thread; queued-but-undispatched requests
        fail with :class:`ServingError` (idempotent)."""
        with self._cond:
            self._running = False
            orphans = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._cond.notify_all()
        for r in orphans:
            r.fail(ServingError("service shut down before dispatch"))
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
