"""Model registry: named, versioned artifacts with warm-up and hot reload.

The reference swaps models by restarting serving processes (a new
``paddle_gradient_machine_create_for_inference`` per deploy); a TPU
serving process cannot afford that — the cold cost is the jit trace +
XLA compile, not the weight load. So the registry makes the expensive
part explicit and keeps it OFF the request path:

- **load = validate + deserialize + warm up + publish.** Warm-up drives
  the freshly loaded :class:`~paddle_tpu.inference.CompiledModel` once
  through ``run()`` and once through ``run_many()`` at every padding
  bucket, with zero feeds shaped from the artifact's own signature — so
  every compiled variant the micro-batcher can ever request exists
  before the first request arrives.
- **hot reload is atomic and behind in-flight requests.** The new
  version is fully built (including warm-up) before a single dict swap
  publishes it; dispatches that already took the old entry keep their
  reference and finish on the old weights. No request ever observes a
  half-loaded model.
- **failed warm-up rolls back.** If validation/deserialize/warm-up of a
  reload raises (fault site ``serving.reload`` — chaos specs can arm it
  via ``PADDLE_TPU_FAULT_SPEC``), the serving version stays published, a
  ``reload_rollback`` degradation event is recorded, and the error
  propagates to the reloader alone.
"""
from __future__ import annotations

import threading
import time

from ..inference import load_compiled
from ..resilience import fault_point, record_event
from .admission import ModelUnavailableError
from .batcher import padding_buckets
# the shared lock constructor: plain threading primitives normally, the
# lock-order race detector's instrumented ones under PADDLE_TPU_SANITIZE=locks
from ..analysis import locks as _locks

__all__ = ["ModelEntry", "ModelRegistry"]


class ModelEntry(object):
    """One published (name, version): immutable once published."""

    __slots__ = ("name", "version", "dirname", "model", "loaded_at",
                 "warmup_ms", "warm_buckets")

    def __init__(self, name, version, dirname, model, warmup_ms,
                 warm_buckets):
        self.name = name
        self.version = version
        self.dirname = dirname
        self.model = model
        self.loaded_at = time.time()
        self.warmup_ms = warmup_ms
        self.warm_buckets = tuple(warm_buckets)

    def describe(self):
        return {"version": self.version, "dirname": self.dirname,
                "loaded_at": self.loaded_at,
                "warmup_ms": round(self.warmup_ms, 3),
                "warm_buckets": list(self.warm_buckets),
                "feed_names": list(self.model.feed_names),
                "fetch_names": list(self.model.fetch_names)}


class ModelRegistry(object):
    def __init__(self, warm_buckets=None):
        """``warm_buckets``: stack depths to pre-trigger at load time;
        defaults to ``padding_buckets(FLAGS.serve_max_batch)`` so the
        registry and the micro-batcher agree without plumbing."""
        if warm_buckets is None:
            from ..flags import FLAGS
            warm_buckets = padding_buckets(FLAGS.serve_max_batch)
        self.warm_buckets = tuple(sorted(set(int(b) for b in warm_buckets)))
        self._models = {}       # name -> ModelEntry
        self._versions = {}     # name -> last assigned version int
        self._lock = _locks.make_lock("serving.registry.state")

    # -- lookup (reads snapshot under the lock: a concurrent first load
    # of a NEW name mutates the dict mid-iteration otherwise) ---------------
    def get(self, name):
        with self._lock:
            entry = self._models.get(name)
            registered = sorted(self._models) if entry is None else None
        if entry is None:
            raise ModelUnavailableError(
                "no model registered under %r (registered: %s)"
                % (name, registered or "none"))
        return entry

    def names(self):
        with self._lock:
            return sorted(self._models)

    def versions(self):
        """{name: published version} snapshot."""
        with self._lock:
            return {n: e.version for n, e in self._models.items()}

    def info(self):
        with self._lock:
            entries = sorted(self._models.items())
        return {n: e.describe() for n, e in entries}

    # -- load / reload -------------------------------------------------------
    def load(self, name, dirname, warm=True):
        """Load (or hot-reload) ``dirname`` as ``name``. Blocks the
        caller for the full validate+deserialize+warm-up cost; the
        request path never blocks — it serves the previous version until
        the single-assignment publish below. Raises (with a rollback
        event when a previous version keeps serving) on any failure."""
        prev = self._models.get(name)
        try:
            model = load_compiled(dirname)
            warmup_ms = self._warm_up(model, name) if warm else 0.0
        except BaseException as e:
            if prev is not None:
                record_event("reload_rollback", site="serving.reload",
                             model=name, kept_version=prev.version,
                             dirname=dirname, error=repr(e))
            raise
        with self._lock:
            version = self._versions.get(name, 0) + 1
            self._versions[name] = version
            entry = ModelEntry(name, version, dirname, model, warmup_ms,
                               self.warm_buckets if warm else ())
            # the publish: one dict assignment, atomic under the GIL —
            # in-flight batches hold the old entry and finish on it
            self._models[name] = entry
        record_event("model_loaded", site="serving.reload", model=name,
                     version=version, dirname=dirname,
                     warmup_ms=round(warmup_ms, 3))
        return entry

    reload = load

    def unload(self, name):
        with self._lock:
            return self._models.pop(name, None) is not None

    def _warm_up(self, model, name):
        """Pre-trigger the jit at the single-request path and at every
        padding bucket, with zeros shaped from the artifact signature.
        ``serving.reload`` fires first so chaos specs can fail a reload
        exactly where a real bad artifact would."""
        import numpy as np
        t0 = time.monotonic()
        fault_point("serving.reload")
        zeros = {n: np.zeros(shape, dtype=dtype)
                 for n, (shape, dtype) in model.feed_spec.items()}
        outs = model.run(zeros)
        for b in self.warm_buckets:
            if b > 1:
                stacked = {n: np.stack([z] * b) for n, z in zeros.items()}
                outs = model.run_many(stacked)
        # a warm-up that silently produced nothing is a broken artifact
        if not list(outs):
            raise ValueError("warm-up of %r produced no outputs" % name)
        return (time.monotonic() - t0) * 1e3
