"""Control flow layers DSL: While, StaticRNN, DynamicRNN, IfElse, Switch,
LoDTensorArray helpers, beam search.

reference: python/paddle/fluid/layers/control_flow.py (While:607,
StaticRNN:237, DynamicRNN:1349, IfElse, Switch, array_write/read/length,
lod_rank_table, lod_tensor_to_array, array_to_lod_tensor, shrink_memory,
max_sequence_len, increment, less_than, equal, reorder_lod_tensor_by_rank)
and layers/nn.py beam_search.
"""
from __future__ import annotations

import contextlib

from ..core import ir
from ..core.types import VarType
from .layer_helper import LayerHelper

__all__ = [
    "While", "StaticRNN", "DynamicRNN", "IfElse", "Switch", "array_write",
    "array_read", "array_length", "create_array", "less_than", "less_equal",
    "greater_than", "greater_equal", "equal", "not_equal", "logical_and",
    "logical_or", "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
    "array_to_lod_tensor", "shrink_memory", "reorder_lod_tensor_by_rank",
    "beam_search", "beam_search_decode", "zeros_like",
    "split_lod_tensor", "merge_lod_tensor", "Print",
]


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Print the tensor whenever it is accessed (works under jit via a
    debug callback). ``first_n`` caps how many times this op prints;
    ``summarize`` caps the printed element count.
    reference: layers/control_flow.py:149 Print -> operators/print_op.cc.
    ``print_phase='backward'`` is fully silent: the reference prints
    only gradients in that phase and this op is no-gradient here, so
    the faithful behavior is to emit nothing (not to print the forward
    tensor)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    out.lod_level = getattr(input, "lod_level", 0)
    helper.append_op(
        type="print", inputs={"In": [input]},
        outputs={"Out": [out]},
        attrs={"first_n": first_n, "summarize": summarize,
               "message": message or "",
               "print_tensor_name": print_tensor_name,
               "print_tensor_type": print_tensor_type,
               "print_tensor_shape": print_tensor_shape,
               "print_tensor_lod": print_tensor_lod,
               "print_phase": str(print_phase).upper()})
    return out


# -- compare / logical -------------------------------------------------------

def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type, **{"x": x, "y": y})
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
    cond.shape = x.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def logical_and(x, y, out=None):
    return _cmp("logical_and", x, y, out)


def logical_or(x, y, out=None):
    return _cmp("logical_or", x, y, out)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


# -- LoDTensorArray ----------------------------------------------------------

def create_array(dtype):
    helper = LayerHelper("array", **{"dtype": dtype})
    return helper.main_block.create_var(
        name="{0}.out".format(helper.name), dtype=dtype,
        type=VarType.LOD_TENSOR_ARRAY)


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", **locals())
    if array is None:
        array = helper.main_block.create_var(
            name="{0}.out".format(helper.name), dtype=x.dtype,
            type=VarType.LOD_TENSOR_ARRAY)
    if array.shape is None:
        array.shape = x.shape
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]},
                     outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", **locals())
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    out.shape = array.shape
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length", **locals())
    out = helper.create_variable_for_type_inference(dtype="int64")
    out.stop_gradient = True
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


# -- rank-table machinery ----------------------------------------------------

def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table", **locals())
    table = helper.main_block.create_var(
        name="{0}.out".format(helper.name), type=VarType.LOD_RANK_TABLE,
        dtype="int32", stop_gradient=True)
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [table]}, attrs={"level": level})
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_length", **locals())
    res = helper.create_variable_for_type_inference(dtype="int64")
    res.stop_gradient = True
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [res]})
    return res


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array", **locals())
    array = helper.main_block.create_var(
        name="{0}.out".format(helper.name), dtype=x.dtype,
        type=VarType.LOD_TENSOR_ARRAY)
    array.shape = x.shape
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [array]})
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor", **locals())
    tmp = helper.create_variable_for_type_inference(dtype=x.dtype)
    tmp.lod_level = 1
    tmp.shape = x.shape
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [tmp]})
    return tmp


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.lod_level = x.lod_level
    out.shape = x.shape
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


# -- While -------------------------------------------------------------------

class BlockGuard(object):
    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.program.rollback()
        return exc_type is None


def _block_reads_writes(sub):
    """Outer vars a sub-block reads / writes (flat namespace)."""
    written, read = [], []
    for op in sub.ops:
        for n in op.input_arg_names:
            if n not in read and n not in written:
                read.append(n)
        for n in op.output_arg_names:
            if n not in written:
                written.append(n)
    return read, written


class While(object):
    """reference: layers/control_flow.py:607. Usage:
        cond = layers.less_than(i, n)
        w = While(cond)
        with w.block():
            ... ops; must update cond ...
    Runs on the eager executor path (data-dependent iteration shapes).
    Reads/writes of the body are declared as op inputs/outputs so
    append_backward's path walk reaches upstream producers, and while_grad
    (per-iteration vjp BPTT) trains through the loop."""

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        read, written = _block_reads_writes(sub)
        parent_block.append_op(
            type="while",
            inputs={"Condition": [self.cond_var],
                    "X": [n for n in read if n != self.cond_var.name]},
            outputs={"Out": list(written)},
            attrs={"sub_block": sub.idx})


# -- StaticRNN (jittable scan) ----------------------------------------------

class StaticRNN(object):
    """Static-length RNN: step block traced into one lax.scan.
    reference: layers/control_flow.py StaticRNN:237 / operators/recurrent_op.
    Sequence inputs carry time on axis 0 ([T, batch, ...])."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None, is_reverse=False):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.is_reverse = is_reverse
        self._x = []          # (outer var, inner var)
        self._mems = []       # (boot var, pre var, post var or None)
        self._outputs = []    # (inner var, outer var)
        self._sub = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self.status = StaticRNN.IN_RNN_BLOCK
        self._sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        self.status = StaticRNN.AFTER_RNN_BLOCK
        self._complete()

    def _assert_in_rnn(self):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError("this method must be called inside rnn.step()")

    def step_input(self, x):
        self._assert_in_rnn()
        inner = self._sub.create_var(
            name="%s@in@%d" % (self.helper.name, len(self._x)),
            dtype=x.dtype, shape=tuple(x.shape[1:]) if x.shape else None)
        self._x.append((x, inner))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1, dtype="float32"):
        self._assert_in_rnn()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            from . import tensor as _tensor
            parent = self.helper.main_program.blocks[self._sub.parent_idx]
            # create the boot var in the parent block
            with _in_block(self.helper.main_program, parent):
                init = _tensor.fill_constant_batch_size_like(
                    input=batch_ref, shape=([-1] + list(shape)),
                    dtype=dtype, value=value,
                    input_dim_idx=ref_batch_dim_idx,
                    output_dim_idx=init_batch_dim_idx)
        pre = self._sub.create_var(
            name="%s@mem@%d" % (self.helper.name, len(self._mems)),
            dtype=init.dtype, shape=init.shape)
        self._mems.append([init, pre, None])
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn()
        for m in self._mems:
            if m[1] is mem:
                m[2] = var
                return
        raise ValueError("update_memory: unknown memory var")

    def step_output(self, o):
        self._assert_in_rnn()
        outer = self._sub.create_var(
            name="%s@out@%d" % (self.helper.name, len(self._outputs)),
            dtype=o.dtype)
        self._outputs.append((o, outer))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        for m in self._mems:
            if m[2] is None:
                raise ValueError("memory %s never updated" % m[1].name)
        # params = outer vars read by the step block but not defined in it
        inner_names = set()
        for op in self._sub.ops:
            inner_names.update(op.output_arg_names)
        inner_names.update(v.name for _, v in self._x)
        inner_names.update(m[1].name for m in self._mems)
        p_names = []
        for op in self._sub.ops:
            for n in op.input_arg_names:
                if n not in inner_names and n not in p_names:
                    p_names.append(n)
        parent = self.helper.main_program.blocks[self._sub.parent_idx]
        out_vars = []
        for (inner, outer) in self._outputs:
            ov = parent.create_var(name=outer.name, dtype=inner.dtype)
            out_vars.append(ov)
        final_mems = [
            parent.create_var(name="%s@final@%d" % (self.helper.name, i),
                              dtype=m[0].dtype)
            for i, m in enumerate(self._mems)]
        parent.append_op(
            type="recurrent",
            inputs={"X": [x for x, _ in self._x],
                    "Boot": [m[0] for m in self._mems],
                    "P": [parent._find_var_recursive(n) or n
                          for n in p_names]},
            outputs={"Out": out_vars, "FinalMems": final_mems},
            attrs={"sub_block": self._sub.idx,
                   "x_inner": [v.name for _, v in self._x],
                   "mem_pre": [m[1].name for m in self._mems],
                   "mem_post": [m[2].name for m in self._mems],
                   "p_names": p_names,
                   "out_inner": [o.name for o, _ in self._outputs],
                   "is_reverse": self.is_reverse})
        self._out_vars = out_vars

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("RNN output can only be retrieved after step()")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


# -- DynamicRNN (eager, rank-table driven) ----------------------------------

@contextlib.contextmanager
def _in_block(program, block):
    """Temporarily emit ops into ``block``."""
    saved = program._current_block_idx
    program._current_block_idx = block.idx
    try:
        yield
    finally:
        program._current_block_idx = saved


class DynamicRNN(object):
    """Ragged-batch RNN over LoD input — the reference's While/rank-table
    construction (batch shrinks as short sequences end).
    reference: layers/control_flow.py:1349. Runs eagerly; the jit path for
    the same models is dynamic_lstm/dynamic_gru (masked scan)."""

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self.lod_rank_table = None
        self.max_seq_len = None
        self.step_idx = None
        self.zero_idx = None
        self.mem_dict = {}
        self.output_array = []
        self.outputs = []
        self.cond = None
        self.input_array = []
        self.mem_link = []
        self._outer_block = None

    @contextlib.contextmanager
    def block(self):
        from . import tensor as _tensor
        if self.status != DynamicRNN.BEFORE_RNN:
            raise ValueError("block() can only be executed once")
        self._outer_block = self.helper.main_program.current_block()
        self.step_idx = _tensor.fill_constant(shape=[1], dtype="int64",
                                              value=0, force_cpu=True)
        self.zero_idx = _tensor.fill_constant(shape=[1], dtype="int64",
                                              value=0, force_cpu=True)
        # cond starts true; the first step_input rewires it to
        # step_idx < max_seq_len, and the loop tail keeps it fresh
        self.cond = self.helper.main_block.create_var(
            name="%s.cond" % self.helper.name, dtype="bool")
        self.cond.stop_gradient = True
        zero = _tensor.fill_constant(shape=[1], dtype="int64", value=0)
        one = _tensor.fill_constant(shape=[1], dtype="int64", value=1)
        less_than(zero, one, cond=self.cond)
        self.status = DynamicRNN.IN_RNN
        w = While(self.cond)
        with w.block():
            yield
            increment(x=self.step_idx, value=1.0, in_place=True)
            for new_mem, mem_array in self.mem_link:
                array_write(x=new_mem, i=self.step_idx, array=mem_array)
            less_than(x=self.step_idx, y=self.max_seq_len, cond=self.cond)
        self.status = DynamicRNN.AFTER_RNN
        for each_array in self.output_array:
            self.outputs.append(
                array_to_lod_tensor(x=each_array, table=self.lod_rank_table))

    def step_input(self, x):
        self._assert_in_rnn_block_("step_input")
        prog = self.helper.main_program
        with _in_block(prog, self._outer_block):
            if self.lod_rank_table is None:
                self.lod_rank_table = lod_rank_table(x)
                self.max_seq_len = max_sequence_len(self.lod_rank_table)
                less_than(x=self.step_idx, y=self.max_seq_len,
                          cond=self.cond)
            input_array = lod_tensor_to_array(x, self.lod_rank_table)
        self.input_array.append((input_array, x.dtype))
        return array_read(array=input_array, i=self.step_idx)

    def static_input(self, x):
        self._assert_in_rnn_block_("static_input")
        if self.lod_rank_table is None:
            raise RuntimeError("static_input() must follow step_input()")
        with _in_block(self.helper.main_program, self._outer_block):
            return reorder_lod_tensor_by_rank(x, self.lod_rank_table)

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        self._assert_in_rnn_block_("memory")
        if self.lod_rank_table is None:
            raise RuntimeError("memory() must follow step_input()")
        prog = self.helper.main_program
        if init is not None:
            with _in_block(prog, self._outer_block):
                boot = reorder_lod_tensor_by_rank(init, self.lod_rank_table)
                mem_array = array_write(x=boot, i=self.zero_idx)
        else:
            from . import tensor as _tensor
            with _in_block(prog, self._outer_block):
                first_in, _ = self.input_array[0]
                first = array_read(array=first_in, i=self.zero_idx)
                boot = _tensor.fill_constant_batch_size_like(
                    input=first, shape=[-1] + list(shape), dtype=dtype,
                    value=value)
                mem_array = array_write(x=boot, i=self.zero_idx)
        retv = array_read(array=mem_array, i=self.step_idx)
        retv = shrink_memory(retv, self.step_idx, self.lod_rank_table)
        self.mem_dict[retv.name] = mem_array
        return retv

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block_("update_memory")
        mem_array = self.mem_dict.get(ex_mem.name)
        if mem_array is None:
            raise ValueError("update_memory: unknown memory")
        self.mem_link.append((new_mem, mem_array))

    def output(self, *outputs):
        self._assert_in_rnn_block_("output")
        for each in outputs:
            outside_array = array_write(x=each, i=self.step_idx)
            self.output_array.append(outside_array)

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("outputs can only be retrieved after the block")
        if len(self.outputs) == 1:
            return self.outputs[0]
        return self.outputs

    def _assert_in_rnn_block_(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("{0} can only be invoked inside rnn block"
                            .format(method))


# -- split/merge_lod_tensor + IfElse / Switch --------------------------------

def split_lod_tensor(input, mask, level=0):
    """Split ``input`` rows (or whole sequences at lod ``level``) by the
    boolean column ``mask`` into (true_branch, false_branch).

    reference: layers/control_flow.py:55 -> operators/split_lod_tensor_op.cc.
    TPU contract: outputs keep input's full row capacity; selected rows are
    stably compacted to the front, the tail is zeros (see the op docstring
    in ops/control_flow_ops.py for the padding contract)."""
    helper = LayerHelper("split_lod_tensor", **locals())
    out_true = helper.create_variable_for_type_inference(input.dtype)
    out_false = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="split_lod_tensor",
        inputs={"X": [input], "Mask": [mask]},
        outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
        attrs={"level": level})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    """Inverse of split_lod_tensor: reassemble rows by ``mask`` position.

    reference: layers/control_flow.py:101 -> operators/merge_lod_tensor_op.cc.
    ``x`` supplies the output's shape/LoD frame (the reference reads its lod;
    here it also carries lod_level for sequence merges)."""
    helper = LayerHelper("merge_lod_tensor", **locals())
    out = helper.create_variable_for_type_inference(in_true.dtype)
    helper.append_op(
        type="merge_lod_tensor",
        inputs={"X": [x], "Mask": [mask], "InTrue": [in_true],
                "InFalse": [in_false]},
        outputs={"Out": [out]},
        attrs={"level": level})
    return out


class IfElse(object):
    """Row-masked two-branch construct.

    reference: layers/control_flow.py:1247 IfElse — the condition is a
    boolean column over batch rows; ``input(x)`` yields the branch's masked
    slice via split_lod_tensor, ``output(...)`` registers branch results,
    and ``__call__`` merges them back row-by-row with merge_lod_tensor.

    TPU-first inversion: the reference wraps each branch in a
    ConditionalBlock that the interpreter may skip at runtime; here BOTH
    branches trace unconditionally on fixed-capacity masked tensors, so the
    whole construct (and its gradient) compiles into one XLA program — no
    host round-trip. Rows a branch does not own are zero-padded by split
    and never selected by merge, so values and grads match the reference's
    dynamic-row semantics for row-wise branch computation (the IfElse
    contract). A scalar (1-row) condition degenerates to classic if/else."""

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.output_table = ([], [])  # (false_outs, true_outs) — ref order
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    @contextlib.contextmanager
    def _guard(self, is_true):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("You cannot invoke IfElse.block() inside a block")
        self.status = (IfElse.IN_IF_ELSE_TRUE_BLOCKS if is_true
                       else IfElse.IN_IF_ELSE_FALSE_BLOCKS)
        try:
            yield
        finally:
            self.status = IfElse.OUT_IF_ELSE_BLOCKS
        if len(self.output_table[1 if is_true else 0]) == 0:
            raise ValueError("Must set output inside block")

    def true_block(self):
        return self._guard(True)

    def false_block(self):
        return self._guard(False)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input must be called inside true/false blocks")
        if id(x) not in self.input_table:
            self.input_table[id(x)] = split_lod_tensor(x, self.cond, level=0)
        out_true, out_false = self.input_table[id(x)]
        return (out_true if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
                else out_false)

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output can only be invoked inside a block")
        table = self.output_table[
            1 if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS else 0]
        table.extend(outs)

    def __call__(self):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("IfElse::__call__ must be out of sub-block")
        false_len, true_len = map(len, self.output_table)
        if false_len == 0 and true_len == 0:
            raise ValueError(
                "Must invoke true_block/false_block before __call__")
        if false_len != true_len and false_len != 0 and true_len != 0:
            raise ValueError("The output side must be same")
        if false_len == 0 or true_len == 0:
            return list(self.output_table[0 if false_len != 0 else 1])
        return [
            merge_lod_tensor(in_true=true_var, in_false=false_var,
                             mask=self.cond, x=self.cond, level=0)
            for false_var, true_var in zip(*self.output_table)]


class Switch(object):
    """reference: layers/control_flow.py Switch — chained conditional
    blocks; each case runs iff its condition holds and no earlier case
    fired (implemented by chaining not-conds)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.pre_not_conds = []

    @contextlib.contextmanager
    def case(self, condition):
        program = self.helper.main_program
        parent = program.current_block()
        conds = [condition]
        for nc in self.pre_not_conds:
            conds.append(nc)
        notv = self.helper.create_variable_for_type_inference("bool")
        parent.append_op(type="logical_not", inputs={"X": [condition]},
                         outputs={"Out": [notv]})
        self.pre_not_conds.append(notv)
        sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        read, written = _block_reads_writes(sub)
        parent.append_op(type="conditional_block",
                         inputs={"Cond": conds, "X": read},
                         outputs={"Out": written},
                         attrs={"sub_block": sub.idx})

    @contextlib.contextmanager
    def default(self):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        read, written = _block_reads_writes(sub)
        parent.append_op(type="conditional_block",
                         inputs={"Cond": list(self.pre_not_conds), "X": read},
                         outputs={"Out": written},
                         attrs={"sub_block": sub.idx})


# -- beam search --------------------------------------------------------------

def beam_search(pre_ids, ids, scores, beam_size, end_id, level=0):
    """reference: layers/nn.py beam_search -> operators/beam_search_op."""
    helper = LayerHelper("beam_search", **locals())
    selected_scores = helper.create_variable_for_type_inference("float32")
    selected_ids = helper.create_variable_for_type_inference("int64")
    selected_ids.lod_level = selected_scores.lod_level = 2
    helper.append_op(type="beam_search",
                     inputs={"pre_ids": [pre_ids], "ids": [ids],
                             "scores": [scores]},
                     outputs={"selected_ids": [selected_ids],
                              "selected_scores": [selected_scores]},
                     attrs={"level": level, "beam_size": beam_size,
                            "end_id": end_id})
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, name=None):
    """reference: layers/nn.py beam_search_decode."""
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_variable_for_type_inference("int64")
    sentence_scores = helper.create_variable_for_type_inference("float32")
    sentence_ids.lod_level = sentence_scores.lod_level = 2
    helper.append_op(type="beam_search_decode",
                     inputs={"Ids": [ids], "Scores": [scores]},
                     outputs={"SentenceIds": [sentence_ids],
                              "SentenceScores": [sentence_scores]})
    return sentence_ids, sentence_scores


# increment lives in tensor.py in the reference; re-export for While loops
from .tensor import increment  # noqa: E402,F401
