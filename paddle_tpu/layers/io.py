"""Input layers. reference: python/paddle/fluid/layers/io.py (data:…,
ListenAndServ:102, Send:173 — the send/recv pair becomes sharding in
paddle_tpu.parallel; `data` remains the feed declaration)."""
from __future__ import annotations

from ..core import ir
from ..core.types import VarType

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=VarType.LOD_TENSOR, stop_gradient=True):
    """Declare a feed variable. reference: layers/io.py data()."""
    helper_block = ir.default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper_block.create_var(name=name, shape=shape, dtype=dtype,
                                   lod_level=lod_level, type=type,
                                   stop_gradient=stop_gradient)
