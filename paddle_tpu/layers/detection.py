"""Detection layers DSL: SSD pipeline (priors, matching, loss, output).

reference: python/paddle/fluid/layers/detection.py (detection_output:46,
detection_map:138, bipartite_match:175, target_assign:245, ssd_loss:317,
multi_box_head:532) + layers/ops auto-generated prior_box/iou_similarity/
box_coder wrappers.
"""
from __future__ import annotations

import numpy as np

from ..param_attr import ParamAttr
from .layer_helper import LayerHelper

__all__ = [
    "prior_box", "iou_similarity", "box_coder", "bipartite_match",
    "target_assign", "mine_hard_examples", "multiclass_nms",
    "multiclass_nms_padded",
    "detection_output", "detection_map", "ssd_loss", "multi_box_head",
    "roi_pool",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    helper = LayerHelper("prior_box", **locals())
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    boxes.stop_gradient = variances.stop_gradient = True
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
               "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    return boxes, variances


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = x.lod_level
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", name=None):
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": [prior_box],
                             "PriorBoxVar": [prior_box_var],
                             "TargetBox": [target_box]},
                     outputs={"OutputBox": [out]},
                     attrs={"code_type": code_type})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_variable_for_type_inference("int32")
    match_distance = helper.create_variable_for_type_inference("float32")
    match_indices.stop_gradient = match_distance.stop_gradient = True
    helper.append_op(type="bipartite_match",
                     inputs={"DistMat": [dist_matrix]},
                     outputs={"ColToRowMatchIndices": [match_indices],
                              "ColToRowMatchDist": [match_distance]},
                     attrs={"match_type": match_type or "bipartite",
                            "dist_threshold": dist_threshold or 0.5})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    out.stop_gradient = out_weight.stop_gradient = True
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [out_weight]},
                     attrs={"mismatch_value": mismatch_value or 0})
    return out, out_weight


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       name=None):
    helper = LayerHelper("mine_hard_examples", **locals())
    neg_indices = helper.create_variable_for_type_inference("int32")
    neg_indices.lod_level = 1
    updated = helper.create_variable_for_type_inference("int32")
    neg_indices.stop_gradient = updated.stop_gradient = True
    helper.append_op(type="mine_hard_examples",
                     inputs={"ClsLoss": [cls_loss],
                             "MatchIndices": [match_indices]},
                     outputs={"NegIndices": [neg_indices],
                              "UpdatedMatchIndices": [updated]},
                     attrs={"neg_pos_ratio": neg_pos_ratio})
    return neg_indices, updated


def multiclass_nms(bboxes, scores, background_label=0, score_threshold=0.01,
                   nms_top_k=400, nms_threshold=0.3, keep_top_k=200,
                   name=None):
    helper = LayerHelper("multiclass_nms", **locals())
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    out.lod_level = 1
    out.stop_gradient = True
    helper.append_op(type="multiclass_nms",
                     inputs={"BBoxes": [bboxes], "Scores": [scores]},
                     outputs={"Out": [out]},
                     attrs={"background_label": background_label,
                            "score_threshold": score_threshold,
                            "nms_top_k": nms_top_k,
                            "nms_threshold": nms_threshold,
                            "keep_top_k": keep_top_k})
    return out


def multiclass_nms_padded(bboxes, scores, background_label=0,
                          score_threshold=0.01, nms_top_k=400,
                          nms_threshold=0.3, keep_top_k=200, name=None):
    """Device-native fixed-capacity NMS: (out [N, keep_top_k, 6],
    valid_count [N]) — compiles into exported inference programs (the
    TPU-native serving form of multiclass_nms; see the op docstring)."""
    helper = LayerHelper("multiclass_nms_padded", **locals())
    out = helper.create_variable_for_type_inference("float32")
    valid = helper.create_variable_for_type_inference("int32")
    out.stop_gradient = valid.stop_gradient = True
    helper.append_op(type="multiclass_nms_padded",
                     inputs={"BBoxes": [bboxes], "Scores": [scores]},
                     outputs={"Out": [out], "ValidCount": [valid]},
                     attrs={"background_label": background_label,
                            "score_threshold": score_threshold,
                            "nms_top_k": nms_top_k,
                            "nms_threshold": nms_threshold,
                            "keep_top_k": keep_top_k})
    return out, valid


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, name=None,
                     padded=False):
    """Decode + per-class NMS. reference: layers/detection.py:46.

    ``padded=True`` routes to the device-native fixed-capacity NMS and
    returns (out, valid_count) — the jittable/exportable serving path."""
    from . import nn as _nn
    from . import tensor as _tensor
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = _nn.transpose(scores, perm=[0, 2, 1])  # [N, C, M]
    nms = multiclass_nms_padded if padded else multiclass_nms
    return nms(decoded, scores_t,
               background_label=background_label,
               score_threshold=score_threshold,
               nms_top_k=nms_top_k, nms_threshold=nms_threshold,
               keep_top_k=keep_top_k)


def detection_map(detect_res, label, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_version="integral"):
    helper = LayerHelper("detection_map", **locals())
    map_out = helper.create_variable_for_type_inference("float32")
    pos_count = helper.create_variable_for_type_inference("int32")
    true_pos = helper.create_variable_for_type_inference("float32")
    false_pos = helper.create_variable_for_type_inference("float32")
    for v in (map_out, pos_count, true_pos, false_pos):
        v.stop_gradient = True
    helper.append_op(type="detection_map",
                     inputs={"DetectRes": [detect_res], "Label": [label]},
                     outputs={"MAP": [map_out],
                              "AccumPosCount": [pos_count],
                              "AccumTruePos": [true_pos],
                              "AccumFalsePos": [false_pos]},
                     attrs={"overlap_threshold": overlap_threshold,
                            "ap_type": ap_version})
    return map_out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative",
             name=None):
    """SSD multibox loss: match, mine hard negatives, localisation smooth-l1
    + confidence softmax loss. reference: layers/detection.py:317 ssd_loss
    (and gserver MultiBoxLossLayer)."""
    from . import nn as _nn
    from . import tensor as _tensor

    # 1. match priors to gt by IoU
    iou = iou_similarity(x=gt_box, y=prior_box)
    matched_indices, matched_dist = bipartite_match(iou, match_type,
                                                    overlap_threshold)
    # 2. confidence loss for mining (targets as constants)
    gt_label_t, _ = target_assign(gt_label, matched_indices,
                                  mismatch_value=background_label)
    # conf: [N, M, C]; cross entropy per prior
    conf_sm = _nn.softmax(confidence)
    cls_loss = _cross_entropy_3d(conf_sm, gt_label_t)
    # 3. hard-negative mining as a dense device mask (r4): same weights
    # the host mine_hard_examples + target_assign(NegIndices) pair
    # produces, but fixed-shape — the whole ssd_loss jit-compiles
    # instead of segmenting around host ops every step
    conf_weight = _ssd_conf_weight(cls_loss, matched_indices,
                                   neg_pos_ratio)
    # negatives carry the background label either way, so the plain
    # match-gather target (already computed) IS the final conf target
    conf_target = gt_label_t
    enc = box_coder(prior_box,
                    prior_box_var if prior_box_var is not None else
                    _tensor.ones([prior_box.shape[0] or 1, 4], "float32"),
                    gt_box, code_type="encode_center_size")
    loc_target, loc_weight = target_assign(enc, matched_indices,
                                           mismatch_value=0)
    # 4. losses
    loc_diff = _nn.elementwise_sub(location, loc_target)
    loc_l = _nn.reduce_sum(
        _smooth_l1(loc_diff), dim=-1, keep_dim=True)
    loc_l = _nn.elementwise_mul(loc_l, loc_weight)
    conf_l = _cross_entropy_3d(conf_sm, conf_target)
    conf_l = _nn.elementwise_mul(_nn.unsqueeze(conf_l, [2]), conf_weight)
    loss = _nn.elementwise_add(
        _nn.scale(loc_l, scale=loc_loss_weight),
        _nn.scale(conf_l, scale=conf_loss_weight))
    return loss


def _ssd_conf_weight(cls_loss, match_indices, neg_pos_ratio):
    helper = LayerHelper("ssd_hard_neg_mask")
    w = helper.create_variable_for_type_inference("float32")
    w.stop_gradient = True
    helper.append_op(type="ssd_hard_neg_mask",
                     inputs={"ClsLoss": [cls_loss],
                             "MatchIndices": [match_indices]},
                     outputs={"ConfWeight": [w]},
                     attrs={"neg_pos_ratio": neg_pos_ratio})
    return w


def _smooth_l1(x):
    from . import nn as _nn
    from .layer_helper import LayerHelper
    helper = LayerHelper("ssd_smooth_l1")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="smooth_l1_core", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={})
    return out


def _cross_entropy_3d(probs, labels):
    """-log p[label] over the last axis of [N, M, C] probs; labels
    [N, M, 1] int."""
    helper = LayerHelper("ce3d")
    out = helper.create_variable_for_type_inference(probs.dtype)
    helper.append_op(type="gather_neg_log", inputs={"X": [probs],
                                                    "Label": [labels]},
                     outputs={"Out": [out]}, attrs={})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=None, flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None):
    """Per-feature-map loc/conf conv heads + concatenated priors.
    reference: layers/detection.py:532 multi_box_head."""
    from . import nn as _nn
    from . import tensor as _tensor

    n_layers = len(inputs)
    if min_sizes is None:
        # reference's ratio interpolation
        min_ratio = min_ratio if min_ratio is not None else 20
        max_ratio = max_ratio if max_ratio is not None else 90
        step = int((max_ratio - min_ratio) / max(n_layers - 2, 1))
        min_sizes, max_sizes = [], []
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_layers - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_layers - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, inp in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        mins = mins if isinstance(mins, (list, tuple)) else [mins]
        maxs = ([maxs] if maxs and not isinstance(maxs, (list, tuple))
                else maxs)
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        box, var = prior_box(inp, image, mins, maxs, ar, variance, flip,
                             clip, steps[i] if steps else None, offset)
        num_priors = (len(ar) * (2 if flip else 1) - (len(ar) - 1 if flip
                      else 0))
        # priors per location = len(expanded ars) per min + one per max
        num_boxes = box.shape[2] if box.shape else None
        boxes_all.append(_nn.reshape(box, [-1, 4]))
        vars_all.append(_nn.reshape(var, [-1, 4]))
        np_ = num_boxes
        loc = _nn.conv2d(inp, num_filters=np_ * 4,
                         filter_size=kernel_size, padding=pad,
                         stride=stride)
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        locs.append(_nn.reshape(loc, [loc.shape[0] or -1, -1, 4]))
        conf = _nn.conv2d(inp, num_filters=np_ * num_classes,
                          filter_size=kernel_size, padding=pad,
                          stride=stride)
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        confs.append(_nn.reshape(conf, [conf.shape[0] or -1, -1,
                                        num_classes]))
    mbox_locs = _tensor.concat(locs, axis=1)
    mbox_confs = _tensor.concat(confs, axis=1)
    box = _tensor.concat(boxes_all, axis=0)
    var = _tensor.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, box, var


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    argmaxes = helper.create_variable_for_type_inference("int32")
    argmaxes.stop_gradient = True
    helper.append_op(type="roi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [out], "Argmax": [argmaxes]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out
