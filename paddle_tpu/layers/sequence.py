"""Sequence layers DSL: RNNs, sequence ops, CRF, CTC.

reference: python/paddle/fluid/layers/nn.py (dynamic_lstm, dynamic_gru,
sequence_conv, sequence_pool, sequence_expand, sequence_softmax,
sequence_first_step, sequence_last_step, linear_chain_crf, crf_decoding,
warpctc, row_conv, lstm_unit, gru_unit, nce) — each appends ops via
LayerHelper, mirroring the reference signatures.
"""
from __future__ import annotations

import numpy as np

from ..param_attr import ParamAttr
from .layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "lstm_unit", "gru_unit",
    "sequence_conv",
    "sequence_pool", "sequence_softmax", "sequence_expand", "sequence_concat",
    "sequence_reshape", "sequence_reverse", "sequence_slice",
    "sequence_erase",
    "sequence_first_step", "sequence_last_step", "lod_reset", "row_conv",
    "linear_chain_crf", "crf_decoding", "warpctc", "ctc_greedy_decoder",
    "chunk_eval", "nce", "kmax_seq_score", "sub_nested_seq",
]


def dynamic_lstm(input, size, param_attr=None, bias_attr=None,
                 use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 h_0=None, c_0=None):
    """Whole-sequence LSTM over a ragged (LoD) batch.
    reference: layers/nn.py dynamic_lstm -> operators/lstm_op.cc. ``input``
    is the [T, 4*hidden] projection (apply fc first, as the reference does);
    ``size`` is 4*hidden."""
    helper = LayerHelper("lstm", **locals())
    hidden = size // 4
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[hidden, 4 * hidden], dtype=dtype)
    h = helper.create_variable_for_type_inference(dtype)
    c = helper.create_variable_for_type_inference(dtype)
    h.lod_level = c.lod_level = input.lod_level
    h.shape = c.shape = tuple(input.shape[:-1]) + (hidden,)
    inputs = {"Input": [input], "Weight": [weight]}
    if bias_attr is not False:
        bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
        inputs["Bias"] = [helper.create_parameter(
            helper.bias_attr or ParamAttr(), shape=bias_size, dtype=dtype,
            is_bias=True)]
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(type="lstm", inputs=inputs,
                     outputs={"Hidden": [h], "Cell": [c]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return h, c


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with a recurrent projection layer.
    reference: layers/nn.py:403 dynamic_lstmp -> operators/lstmp_op.cc.
    ``size`` is 4*hidden; ``proj_size`` the projection width P. Returns
    (projection [T, P], cell [T, hidden])."""
    helper = LayerHelper("lstmp", **locals())
    hidden = size // 4
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[proj_size, 4 * hidden],
                                     dtype=dtype)
    proj_weight = helper.create_parameter(
        ParamAttr(name=(name + ".w_proj") if name else None),
        shape=[hidden, proj_size], dtype=dtype)
    proj = helper.create_variable_for_type_inference(dtype)
    c = helper.create_variable_for_type_inference(dtype)
    proj.lod_level = c.lod_level = input.lod_level
    proj.shape = tuple(input.shape[:-1]) + (proj_size,)
    c.shape = tuple(input.shape[:-1]) + (hidden,)
    inputs = {"Input": [input], "Weight": [weight],
              "ProjWeight": [proj_weight]}
    if bias_attr is not False:
        bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
        inputs["Bias"] = [helper.create_parameter(
            helper.bias_attr or ParamAttr(), shape=bias_size, dtype=dtype,
            is_bias=True)]
    helper.append_op(type="lstmp",
                     inputs=inputs,
                     outputs={"Projection": [proj], "Cell": [c]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation})
    return proj, c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32",
                name=None):
    """reference: layers/nn.py dynamic_gru -> operators/gru_op.cc. ``input``
    is the [T, 3*size] projection; returns hidden [T, size]."""
    helper = LayerHelper("gru", **locals())
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    h = helper.create_variable_for_type_inference(dtype)
    h.lod_level = input.lod_level
    h.shape = tuple(input.shape[:-1]) + (size,)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(type="gru", inputs=inputs, outputs={"Hidden": [h]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    return h


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step on dense tensors (for Static/DynamicRNN bodies).
    reference: layers/nn.py lstm_unit -> operators/lstm_unit_op.cc —
    fc([x, h_prev]) -> 4D gates -> cell update."""
    from . import nn as _nn
    from . import tensor as _tensor
    helper = LayerHelper("lstm_unit", **locals())
    size = cell_t_prev.shape[-1]
    concat_in = _tensor.concat([x_t, hidden_t_prev], axis=1)
    fc_out = _nn.fc(concat_in, size=4 * size, param_attr=param_attr,
                    bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    c.shape = h.shape = cell_t_prev.shape
    helper.append_op(type="lstm_unit",
                     inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """One GRU step. reference: layers/nn.py gru_unit ->
    operators/gru_unit_op.cc; ``size`` is 3*hidden like the reference."""
    helper = LayerHelper("gru_unit", **locals())
    dtype = helper.input_dtype()
    hidden_dim = size // 3
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[hidden_dim, 3 * hidden_dim],
                                     dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                   shape=[1, 3 * hidden_dim], dtype=dtype,
                                   is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    updated = helper.create_variable_for_type_inference(dtype)
    updated.shape = hidden.shape
    helper.append_op(type="gru_unit",
                     inputs={"Input": [input], "HiddenPrev": [hidden],
                             "Weight": [weight], "Bias": [bias]},
                     outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h],
                              "Hidden": [updated]},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    return updated, reset_h, gate


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    """reference: layers/nn.py sequence_conv -> operators/sequence_conv_op."""
    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    pre_bias.lod_level = input.lod_level
    pre_bias.shape = tuple(input.shape[:-1]) + (num_filters,)
    helper.append_op(type="sequence_conv",
                     inputs={"X": [input], "Filter": [filter_param]},
                     outputs={"Out": [pre_bias]},
                     attrs={"contextStride": filter_stride,
                            "contextStart": -int(filter_size // 2),
                            "contextLength": filter_size})
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type, stride=-1):
    """reference: layers/nn.py sequence_pool -> operators/sequence_pool_op.
    ``stride`` > 0 pools stride-sized windows within each sequence to a
    shorter sequence (the v1 SequencePoolLayer stride semantics)."""
    if stride != -1 and stride <= 0:
        raise ValueError(
            "sequence_pool stride must be -1 (whole sequence) or > 0, "
            "got %r" % (stride,))
    helper = LayerHelper("sequence_pool", **locals())
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    max_index = helper.create_variable_for_type_inference(dtype="int32",
                                                          stop_gradient=True)
    if input.shape is not None:
        out.shape = tuple(input.shape)
    out.lod_level = (input.lod_level if stride > 0
                     else max(input.lod_level - 1, 0))
    attrs = {"pooltype": pool_type.upper()}
    if stride > 0:  # default -1 stays un-serialized (golden-config stable)
        attrs["stride"] = int(stride)
    helper.append_op(type="sequence_pool", inputs={"X": [input]},
                     outputs={"Out": [out], "MaxIndex": [max_index]},
                     attrs=attrs)
    return out


def sequence_first_step(input, stride=-1):
    return sequence_pool(input, "first", stride=stride)


def sequence_last_step(input, stride=-1):
    return sequence_pool(input, "last", stride=stride)


def sequence_softmax(input, name=None):
    helper = LayerHelper("sequence_softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape, out.lod_level = input.shape, input.lod_level
    helper.append_op(type="sequence_softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def sequence_expand(x, y, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape, out.lod_level = x.shape, max(y.lod_level, 1)
    helper.append_op(type="sequence_expand", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", **locals())
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    out.lod_level = max(v.lod_level for v in inputs)
    helper.append_op(type="sequence_concat", inputs={"X": list(inputs)},
                     outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.lod_level = input.lod_level
    out.shape = (input.shape[0], new_dim)
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"new_dim": new_dim})
    return out


def sequence_reverse(x, name=None):
    """Reverse each sequence's rows in place (per-sequence flip).
    reference: operators/sequence_reverse_op.h."""
    helper = LayerHelper("sequence_reverse", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = x.lod_level
    out.shape = x.shape
    helper.append_op(type="sequence_reverse", inputs={"X": [x]},
                     outputs={"Y": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    """``offset=None`` slices from each sequence's begin; ``length=None``
    slices to its end (v1 seq_slice_layer's open-ended sides)."""
    helper = LayerHelper("sequence_slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.lod_level = input.lod_level
    inputs = {"X": [input]}
    if offset is not None:
        inputs["Offset"] = [offset]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(type="sequence_slice", inputs=inputs,
                     outputs={"Out": [out]})
    return out


def sequence_erase(input, tokens, name=None):
    helper = LayerHelper("sequence_erase", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.lod_level = input.lod_level
    helper.append_op(type="sequence_erase", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"tokens": list(tokens)})
    return out


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    out.lod_level = 1 if y is None else max(y.lod_level, 1)
    inputs = {"X": [x]}
    attrs = {}
    if y is not None:
        inputs["Y"] = [y]
    elif target_lod is not None:
        attrs["target_lod"] = list(target_lod)
    else:
        raise ValueError("lod_reset needs y or target_lod")
    helper.append_op(type="lod_reset", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: layers/nn.py row_conv -> operators/row_conv_op.cc."""
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape, out.lod_level = input.shape, input.lod_level
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [filter_param]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def linear_chain_crf(input, label, param_attr=None):
    """reference: layers/nn.py linear_chain_crf ->
    operators/linear_chain_crf_op; returns per-sequence -log p(y|x)."""
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(helper.param_attr,
                                         shape=[size + 2, size],
                                         dtype=helper.input_dtype())
    alpha = helper.create_variable_for_type_inference(helper.input_dtype())
    emission_exps = helper.create_variable_for_type_inference(
        helper.input_dtype())
    transition_exps = helper.create_variable_for_type_inference(
        helper.input_dtype())
    log_likelihood = helper.create_variable_for_type_inference(
        helper.input_dtype())
    helper.append_op(type="linear_chain_crf",
                     inputs={"Emission": [input], "Transition": [transition],
                             "Label": [label]},
                     outputs={"Alpha": [alpha],
                              "EmissionExps": [emission_exps],
                              "TransitionExps": [transition_exps],
                              "LogLikelihood": [log_likelihood]})
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    """reference: layers/nn.py crf_decoding -> operators/crf_decoding_op."""
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.get_parameter(param_attr.name)
    viterbi_path = helper.create_variable_for_type_inference(dtype="int64")
    viterbi_path.lod_level = input.lod_level
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [viterbi_path]})
    return viterbi_path


def warpctc(input, label, blank=0, norm_by_times=False):
    """reference: layers/nn.py warpctc -> operators/warpctc_op.cc."""
    helper = LayerHelper("warpctc", **locals())
    loss_out = helper.create_variable_for_type_inference(input.dtype)
    grad_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="warpctc",
                     inputs={"Logits": [input], "Label": [label]},
                     outputs={"WarpCTCGrad": [grad_out], "Loss": [loss_out]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss_out


def ctc_greedy_decoder(input, blank, name=None):
    """argmax over classes + ctc_align (merge repeats, drop blanks).
    reference: layers/nn.py ctc_greedy_decoder."""
    from . import tensor as _tensor
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    top1 = _tensor.argmax(input, axis=-1)
    # keep the lod of the input on the argmax indices
    ids = lod_reset(top1, y=input)
    out = helper.create_variable_for_type_inference(dtype="int64")
    out.lod_level = 1
    helper.append_op(type="ctc_align", inputs={"Input": [ids]},
                     outputs={"Output": [out]},
                     attrs={"blank": blank, "merge_repeated": True})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """reference: layers/nn.py chunk_eval -> operators/chunk_eval_op.cc."""
    helper = LayerHelper("chunk_eval", **locals())
    precision = helper.create_variable_for_type_inference(dtype="float32")
    recall = helper.create_variable_for_type_inference(dtype="float32")
    f1_score = helper.create_variable_for_type_inference(dtype="float32")
    num_infer = helper.create_variable_for_type_inference(dtype="int64")
    num_label = helper.create_variable_for_type_inference(dtype="int64")
    num_correct = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(type="chunk_eval",
                     inputs={"Inference": [input], "Label": [label]},
                     outputs={"Precision": [precision], "Recall": [recall],
                              "F1-Score": [f1_score],
                              "NumInferChunks": [num_infer],
                              "NumLabelChunks": [num_label],
                              "NumCorrectChunks": [num_correct]},
                     attrs={"num_chunk_types": num_chunk_types,
                            "chunk_scheme": chunk_scheme,
                            "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1_score, num_infer, num_label, num_correct


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, sampler="uniform",
        custom_dist=None):
    """Noise-contrastive estimation loss.
    reference: layers/nn.py nce -> operators/nce_op.cc. Negative samples are
    drawn by a separate uniform_random int op feeding a deterministic
    nce_core op, so the generic-vjp grad replays cleanly."""
    helper = LayerHelper("nce", **locals())
    dtype = helper.input_dtype()
    dim = input.shape[-1]
    num_neg = num_neg_samples or 10
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, dim], dtype=dtype)
    b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                shape=[num_total_classes, 1], dtype=dtype,
                                is_bias=True)
    samples = helper.create_variable_for_type_inference(dtype="int64",
                                                        stop_gradient=True)
    if sampler == "log_uniform":
        helper.append_op(type="log_uniform_random_int",
                         outputs={"Out": [samples]},
                         attrs={"shape": [num_neg],
                                "range": num_total_classes})
    elif sampler == "custom_dist":
        if custom_dist is None:
            raise ValueError(
                "nce(sampler='custom_dist') requires custom_dist (a "
                "[num_total_classes] probability variable)")
        # sample via inverse-CDF of the user distribution
        # (reference: operators/math/sampler.h CustomSampler)
        helper.append_op(type="custom_dist_random_int",
                         inputs={"Probs": [custom_dist]},
                         outputs={"Out": [samples]},
                         attrs={"shape": [num_neg]})
    else:
        helper.append_op(type="uniform_random_int",
                         outputs={"Out": [samples]},
                         attrs={"shape": [num_neg], "low": 0,
                                "high": num_total_classes})
    cost = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w],
              "Bias": [b], "Samples": [samples]}
    if sampler == "custom_dist":
        inputs["CustomDistProbs"] = [custom_dist]
    helper.append_op(type="nce_core",
                     inputs=inputs,
                     outputs={"Cost": [cost]},
                     attrs={"num_total_classes": num_total_classes,
                            "num_neg_samples": num_neg,
                            "sampler": sampler})
    cost.shape = (input.shape[0], 1)
    return cost


def kmax_seq_score(input, beam_size=1, name=None):
    """Per-sequence top-beam_size within-sequence indices of a [total, 1]
    score LoD tensor; [n_seqs, beam_size] int64, -1 padded (reference:
    gserver/layers/KmaxSeqScoreLayer.cpp)."""
    helper = LayerHelper("kmax_seq_score", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="kmax_seq_score", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"beam_size": beam_size})
    return out


def sub_nested_seq(input, selected_indices, name=None):
    """Select sub-sequences of a nested sequence by per-outer-sequence
    indices ([n_outer, k], -1 padded); output is a lod level 1 sequence
    (reference: gserver/layers/SubNestedSequenceLayer.cpp)."""
    helper = LayerHelper("sub_nested_seq", **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype())
    helper.append_op(type="sub_nested_seq",
                     inputs={"X": [input],
                             "SelectedIndices": [selected_indices]},
                     outputs={"Out": [out]})
    return out
