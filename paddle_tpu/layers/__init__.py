"""layers DSL — flat namespace like ``fluid.layers.*``
(reference: python/paddle/fluid/layers/__init__.py)."""
from . import io, nn, tensor  # noqa: F401
from .io import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .nn import concat_nn  # noqa: F401

__all__ = []
__all__ += io.__all__
__all__ += nn.__all__
__all__ += tensor.__all__
