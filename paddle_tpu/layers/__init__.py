"""layers DSL — flat namespace like ``fluid.layers.*``
(reference: python/paddle/fluid/layers/__init__.py)."""
from . import control_flow, detection, io, nn, sequence, tensor  # noqa: F401
from .io import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .nn import concat_nn  # noqa: F401
from . import ops as _ops_mod  # noqa: F401

__all__ = []
__all__ += io.__all__
__all__ += nn.__all__
__all__ += sequence.__all__
__all__ += tensor.__all__
__all__ += control_flow.__all__
__all__ += detection.__all__

# auto-generated simple-op layers fill any name not hand-written above
# (reference: fluid/layers/ops.py registered after nn.py the same way)
for _n in _ops_mod.__all__:
    if _n not in globals():
        globals()[_n] = getattr(_ops_mod, _n)
        __all__.append(_n)
del _n
