"""Auto-generated thin layers over registered ops.

reference: python/paddle/fluid/layers/ops.py + layer_function_generator.py —
the reference generates one Python layer per OpProto for simple ops; here the
same idea runs over the op registry's unary/binary tables.
"""
from __future__ import annotations

import sys

from .layer_helper import LayerHelper

_UNARY = [
    "sigmoid", "logsigmoid", "tanh", "relu", "relu6", "exp", "abs", "ceil",
    "floor", "round", "log", "square", "sqrt", "reciprocal", "softplus",
    "softsign", "sin", "cos", "tanh_shrink", "softshrink", "hard_shrink",
    "sign", "brelu", "leaky_relu", "soft_relu", "elu", "swish", "stanh",
    "hard_sigmoid", "thresholded_relu", "pow", "logical_not", "isfinite",
    "cumsum",
]

__all__ = list(_UNARY) + ["gather", "scatter", "uniform_random",
                          "gaussian_random"]


def _make_unary(op_type):
    def layer(x, **attrs):
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = ("Elementwise %s (auto-generated; reference: "
                     "python/paddle/fluid/layers/ops.py)." % op_type)
    return layer


_mod = sys.modules[__name__]
for _op in _UNARY:
    if not hasattr(_mod, _op):
        setattr(_mod, _op, _make_unary(_op))


def gather(input, index):
    """reference: operators/gather_op.cc — rows of ``input`` at ``index``."""
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, overwrite=True):
    """reference: operators/scatter_op.cc."""
    helper = LayerHelper("scatter")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    out.shape = tuple(shape)
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std, "seed": seed})
    out.shape = tuple(shape)
    return out
