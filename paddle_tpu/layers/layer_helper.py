"""LayerHelper: shared parameter/var creation for the layers DSL.

reference: python/paddle/fluid/layer_helper.py — creates parameters in BOTH
the startup program (with their init op) and the main program, appends ops to
the main block, applies default weight/bias initializers and activations.
"""
from __future__ import annotations

import copy

from ..core import ir, unique_name
from ..initializer import (ConstantInitializer, XavierInitializer,
                           default_bias_initializer,
                           default_weight_initializer)
from ..param_attr import ParamAttr


class LayerHelper(object):
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name")
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return ir.default_main_program()

    @property
    def startup_program(self):
        return ir.default_startup_program()

    @property
    def main_block(self):
        return self.main_program.current_block()

    def append_op(self, *args, **kwargs):
        return self.main_block.append_op(*args, **kwargs)

    # -- inputs --------------------------------------------------------------
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, ir.Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly 1 input" % self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [copy.deepcopy(attr) for _ in range(length)]
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        for i, a in zip(inputs, attrs):
            yield i, a

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for v in inputs:
            if dtype is None:
                dtype = v.dtype
            elif dtype != v.dtype:
                raise ValueError("mixed input dtypes in %s" % self.layer_type)
        return dtype

    # -- parameter / var creation --------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        assert isinstance(attr, ParamAttr)
        attr = copy.deepcopy(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))
        init = attr.initializer
        if init is None:
            init = default_initializer
        if init is None:
            init = (default_bias_initializer() if is_bias
                    else default_weight_initializer())
        # startup program: var + init op
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())
        init(sp, startup_block)
        # main program: the parameter the ops reference
        return self.main_block.create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())

    def get_parameter(self, name):
        v = self.main_program.global_block()._find_var_recursive(name)
        if v is None:
            raise ValueError("parameter %r not found" % name)
        return v

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, stop_gradient=stop_gradient)

    # alias used throughout (reference keeps both spellings across versions)
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_block.create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs)

    def set_variable_initializer(self, var, initializer):
        sb = self.startup_program.global_block()
        sv = sb.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                           persistable=True)
        initializer(sv, sb)
        return sv

    # -- bias / activation epilogues ----------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype,
                                  is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        # bias add is row-wise: the output is the same (possibly ragged)
        # batch as the input, so the LoD annotation must flow through —
        # dropping it here breaks the declared lod chain a downstream
        # sequence op needs (analysis rule PT016 polices exactly this)
        tmp.lod_level = getattr(input_var, "lod_level", 0)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [b]},
                       outputs={"Out": [tmp]},
                       attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = copy.deepcopy(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        # activations are elementwise: LoD flows through (see append_bias_op)
        tmp.lod_level = getattr(input_var, "lod_level", 0)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name)
        if not isinstance(param, cls):
            raise TypeError("%s of %s must be %s" % (param_name,
                                                     self.layer_type, cls))
