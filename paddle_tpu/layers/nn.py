"""Core NN layers DSL — each function appends ops to the current block.

reference: python/paddle/fluid/layers/nn.py (fc:151, embedding, conv2d,
pool2d, batch_norm, dropout ... 49 defs via LayerHelper).
"""
from __future__ import annotations

import numpy as np

from ..core import ir
from ..core.types import convert_dtype
from ..initializer import ConstantInitializer
from ..param_attr import ParamAttr
from .layer_helper import LayerHelper

__all__ = [
    "fc", "embedding", "dropout", "conv2d", "conv2d_transpose", "pool2d",
    "batch_norm", "layer_norm", "softmax", "cross_entropy",
    "square_error_cost", "softmax_with_cross_entropy", "accuracy", "auc",
    "topk",
    "matmul", "reshape", "transpose", "split", "concat_nn", "reduce_sum",
    "reduce_mean", "reduce_max", "reduce_min", "l2_normalize", "one_hot",
    "clip", "clip_by_norm", "mean", "mul", "scale", "dot", "cos_sim", "slice",
    "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div", "lrn", "prelu",
    "pad", "label_smooth", "sigmoid_cross_entropy_with_logits", "maxout",
    "relu", "log", "im2sequence", "expand", "squeeze", "unsqueeze",
    "edit_distance", "hsigmoid", "factorization_machine", "multiplex",
    "spp", "max_pool2d_with_index", "unpool", "mdlstm",
    "conv3d", "conv3d_transpose", "pool3d", "smooth_l1",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None, use_mkldnn=False):
    """Fully connected. reference: layers/nn.py:151 (fc) — mul per input +
    sum + bias + act; the mul flattens by num_flatten_dims."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, param_attr_ in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_shape = [
            int(np.prod(input_shape[num_flatten_dims:]))
        ] + [size]
        w = helper.create_parameter(param_attr_, shape=param_shape,
                                    dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="mul",
                         inputs={"X": [input_var], "Y": [w]},
                         outputs={"Out": [tmp]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: layers/nn.py embedding -> lookup_table op."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(helper.param_attr, shape=size, dtype=dtype,
                                is_bias=False)
    tmp = helper.create_variable_for_type_inference(dtype)
    in_shape = input.shape or (-1, 1)
    tmp.shape = tuple(in_shape[:-1] if in_shape[-1] == 1 else in_shape) + (size[1],)
    tmp.lod_level = input.lod_level
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(type="lookup_table",
                     inputs={"Ids": [input], "W": [w]},
                     outputs={"Out": [tmp]},
                     attrs={"is_sparse": is_sparse,
                            "is_distributed": is_distributed,
                            "padding_idx": padding_idx})
    return tmp


def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    mask = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    helper.append_op(type="dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "seed": seed if seed is not None else 0})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None):
    """reference: layers/nn.py conv2d — NCHW, filter OIHW."""
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    def _std(shape):
        fan_in = shape[1] * shape[2] * shape[3]
        return (2.0 / fan_in) ** 0.5

    from ..initializer import NormalInitializer
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, _std(filter_shape)))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="conv2d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups,
                            "use_cudnn": use_cudnn})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None,
                     groups=None):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if num_filters % groups or num_channels % groups:
        raise ValueError(
            "conv2d_transpose: num_filters (%d) and input channels (%d) "
            "must both be divisible by groups (%d)"
            % (num_filters, num_channels, groups))
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    if filter_size is None:
        h, w = input.shape[2], input.shape[3]
        oh, ow = output_size if isinstance(output_size, (list, tuple)) \
            else (output_size, output_size)
        filter_size = [oh - (h - 1) * stride[0] + 2 * padding[0],
                       ow - (w - 1) * stride[1] + 2 * padding[1]]
    elif isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="conv2d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None, groups=None):
    """reference: operators/conv_transpose_op.cc 3d registration (and the
    v1 DeConv3DLayer, gserver/layers/DeConv3DLayer.cpp). NCDHW, filter
    IODHW."""
    helper = LayerHelper("conv3d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    if isinstance(stride, int):
        stride = [stride] * 3
    if isinstance(padding, int):
        padding = [padding] * 3
    if isinstance(dilation, int):
        dilation = [dilation] * 3
    if filter_size is None:
        dims = input.shape[2:5]
        osz = output_size if isinstance(output_size, (list, tuple)) \
            else [output_size] * 3
        filter_size = [osz[i] - (dims[i] - 1) * stride[i] + 2 * padding[i]
                       for i in range(3)]
    elif isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    groups = groups or 1
    if num_filters % groups or num_channels % groups:
        raise ValueError(
            "conv3d_transpose: num_filters (%d) and input channels (%d) "
            "must both be divisible by groups (%d)"
            % (num_filters, num_channels, groups))
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="conv3d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, use_mkldnn=False, name=None, exclusive=True):
    helper = LayerHelper("pool2d", **locals())
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type="pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": pool_size,
                            "strides": pool_stride, "paddings": pool_padding,
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode,
                            "exclusive": exclusive})
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None,
           name=None):
    """reference: fluid layers/nn.py conv3d — NCDHW, filter OIDHW."""
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    if isinstance(stride, int):
        stride = [stride] * 3
    if isinstance(padding, int):
        padding = [padding] * 3
    if isinstance(dilation, int):
        dilation = [dilation] * 3
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    from ..initializer import NormalInitializer
    fan_in = filter_shape[1] * filter_shape[2] * filter_shape[3] * \
        filter_shape[4]
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, (2.0 / fan_in) ** 0.5))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="conv3d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [pre_bias]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None):
    """reference: fluid layers/nn.py pool3d — NCDHW."""
    helper = LayerHelper("pool3d", **locals())
    if isinstance(pool_size, int):
        pool_size = [pool_size] * 3
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride] * 3
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding] * 3
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type, "ksize": pool_size,
                            "strides": pool_stride, "paddings": pool_padding,
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    """Per-row smooth-L1 loss, [N, 1] (reference:
    operators/smooth_l1_loss_op.cc; gserver SmoothL1CostLayer uses
    sigma=1). With a=|x-y|, t=1/sigma^2: 0.5*sigma^2*a^2 for a<t else
    a-0.5*t, summed over the row. Branch-free form:
    0.5*sigma^2*min(a,t)^2 + (a - min(a,t))."""
    from .. import layers as _F
    diff = _F.elementwise_sub(x, y)
    if inside_weight is not None:
        diff = _F.elementwise_mul(diff, inside_weight)
    s2 = float(sigma) * float(sigma)
    t = 1.0 / s2
    a = _F.abs(diff)
    amin = _F.clip(a, 0.0, t)
    quad = _F.scale(_F.elementwise_mul(amin, amin), scale=0.5 * s2)
    per_elem = _F.elementwise_add(quad, _F.elementwise_sub(a, amin))
    if outside_weight is not None:
        per_elem = _F.elementwise_mul(per_elem, outside_weight)
    return _F.reduce_sum(per_elem, dim=1, keep_dim=True)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, use_mkldnn=False, name=None,
               moving_mean_name=None, moving_variance_name=None):
    """reference: layers/nn.py batch_norm — Mean/Variance are persistable vars
    passed as inputs AND outputs so running stats update in-program."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == "NCHW":
        channel_num = input_shape[1]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(helper.param_attr, shape=param_shape,
                                    dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
    from ..param_attr import ParamAttr
    bias = helper.create_parameter(
        helper.bias_attr if helper.bias_attr else ParamAttr(),
        shape=param_shape, dtype=dtype, is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(0.0))
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(dtype,
                                                           stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype,
                                                          stop_gradient=True)
    out = input if in_place else \
        helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(helper.param_attr, shape=param_shape,
                                    dtype=dtype,
                                    default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            helper.bias_attr if helper.bias_attr else ParamAttr(),
            shape=param_shape, dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    mean_out = helper.create_variable_for_type_inference(dtype, True)
    var_out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean_out],
                              "Variance": [var_out]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def softmax(input, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]}, attrs={"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [softmax_out], "Loss": [loss]},
                     attrs={"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(type="square_error_cost",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """reference: layers/nn.py accuracy — topk + accuracy op."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32")
    if total is None:
        total = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="accuracy",
                     inputs={"Out": [topk_out], "Indices": [topk_indices],
                             "Label": [label]},
                     outputs={"Accuracy": [acc_out], "Correct": [correct],
                              "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1):
    """reference: layers/metric.py auc -> operators/auc_op.cc. ``input``
    is the (N, 2) softmax or (N, 1) sigmoid click probability."""
    helper = LayerHelper("auc")
    auc_out = helper.create_variable_for_type_inference("float32")
    auc_out.shape = ()
    auc_out.stop_gradient = True
    helper.append_op(type="auc",
                     inputs={"Out": [input], "Label": [label]},
                     outputs={"AUC": [auc_out]},
                     attrs={"curve": curve,
                            "num_thresholds": num_thresholds})
    return auc_out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def dot(x, y, name=None):
    helper = LayerHelper("dot", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reduce_sum", inputs={"X": [x * y]},
                     outputs={"Out": [out]},
                     attrs={"dim": [-1], "keep_dim": True})
    return out


def _simple(op_type, x, attrs=None, outs=("Out",), dtype=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype or x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]},
                     outputs={outs[0]: [out]}, attrs=attrs or {})
    return out


def mean(x, name=None):
    return _simple("mean", x)


def relu(x, name=None):
    return _simple("relu", x)


def log(x, name=None):
    return _simple("log", x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": scale, "bias": bias,
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def _reduce(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        attrs = {"dim": dim if isinstance(dim, (list, tuple)) else [dim],
                 "keep_dim": keep_dim, "reduce_all": False}
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out) if act else out


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _simple("l2_normalize", x, {"axis": axis, "epsilon": epsilon})


def slice(input, axes, starts, ends, name=None):
    """reference: operators/slice_op.cc."""
    helper = LayerHelper("slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def cos_sim(X, Y):
    """reference: layers/nn.py cos_sim -> operators/cos_sim_op.cc."""
    helper = LayerHelper("cos_sim", **locals())
    out = helper.create_variable_for_type_inference(dtype=X.dtype)
    xnorm = helper.create_variable_for_type_inference(dtype=X.dtype)
    ynorm = helper.create_variable_for_type_inference(dtype=X.dtype)
    out.shape = (X.shape[0], 1) if X.shape else None
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xnorm],
                              "YNorm": [ynorm]})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def clip(x, min, max, name=None):
    return _simple("clip", x, {"min": min, "max": max})


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", x, {"max_norm": max_norm})


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    alpha_shape = [1] if mode == "all" else \
        ([x.shape[1]] if mode == "channel" else list(x.shape[1:]))
    alpha = helper.create_parameter(
        helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", x, {"paddings": paddings, "pad_value": pad_value})


def maxout(x, groups, name=None):
    return _simple("maxout", x, {"groups": groups})


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    """(1-eps)*label + eps*prior (uniform prior when prior_dist is None)."""
    if prior_dist is None:
        return scale(label, 1.0 - epsilon, epsilon / label.shape[-1])
    prior_term = scale(prior_dist, epsilon)
    return elementwise_add(scale(label, 1.0 - epsilon), prior_term)


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    helper = LayerHelper("reshape", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"shape": list(shape)})
    return helper.append_activation(out) if act else out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", **locals())
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    n_out = num if num else len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n_out)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"axis": dim, "num": num, "sections": sections})
    return outs


def concat_nn(input, axis=0, name=None):
    from .tensor import concat as _concat
    return _concat(input, axis, name)


def expand(x, expand_times, name=None):
    return _simple("expand", x, {"expand_times": list(expand_times)})


def squeeze(input, axes, name=None):
    return _simple("squeeze", input, {"axes": list(axes)})


def unsqueeze(input, axes, name=None):
    return _simple("unsqueeze", input, {"axes": list(axes)})


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", **locals())
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if len(padding) == 2:
        padding = padding + padding
    out = helper.create_variable_for_type_inference(input.dtype, )
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": filter_size, "strides": stride,
                            "paddings": padding})
    return out


def edit_distance(input, label, normalized=False, ignored_tokens=None,
                  name=None):
    """Levenshtein distance per sequence pair → ([N,1] distances, [1] count).

    reference: layers/nn.py edit_distance over operators/edit_distance_op.*
    (``ignored_tokens`` are erased before comparison there via an implicit
    sequence_erase; here they ride through as an op attr — apply
    layers.sequence_erase on LoD inputs for identical semantics).
    """
    helper = LayerHelper("edit_distance", **locals())
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": [input], "Refs": [label]},
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized,
                            "ignored_tokens": list(ignored_tokens or [])})
    return out, seq_num


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid classifier over a complete binary tree.
    reference: layers in gserver/layers/HierarchicalSigmoidLayer.cpp /
    fluid operators/hierarchical_sigmoid_op."""
    helper = LayerHelper("hierarchical_sigmoid", **locals())
    dtype = helper.input_dtype()
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_classes - 1, dim], dtype=dtype)
    b = None
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[num_classes - 1, 1], dtype=dtype,
                                    is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (input.shape[0], 1)
    helper.append_op(type="hierarchical_sigmoid",
                     inputs={"X": [input], "W": [w], "Label": [label],
                             "Bias": [b] if b is not None else []},
                     outputs={"Out": [out]},
                     attrs={"num_classes": num_classes})
    return out


def factorization_machine(input, factor_size, param_attr=None, name=None):
    """Second-order factorization machine interaction term.
    reference: gserver/layers/FactorizationMachineLayer.cpp."""
    helper = LayerHelper("factorization_machine", **locals())
    dtype = helper.input_dtype()
    dim = input.shape[-1]
    v = helper.create_parameter(helper.param_attr,
                                shape=[dim, factor_size], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (input.shape[0], 1)
    helper.append_op(type="factorization_machine",
                     inputs={"X": [input], "V": [v]},
                     outputs={"Out": [out]})
    return out


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors by index.
    reference: layers/nn.py multiplex -> operators/multiplex_op.cc."""
    helper = LayerHelper("multiplex", **locals())
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    out.shape = inputs[0].shape
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def spp(input, pyramid_height, pool_type="max", name=None):
    """Spatial pyramid pooling. reference: operators/spp_op.cc."""
    helper = LayerHelper("spp", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    c = input.shape[1] if input.shape else None
    if c is not None:
        bins = sum(4 ** l for l in range(pyramid_height))
        out.shape = (input.shape[0], c * bins)
    helper.append_op(type="spp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": pyramid_height,
                            "pooling_type": pool_type})
    return out


def max_pool2d_with_index(input, pool_size, pool_stride=None,
                          pool_padding=0, name=None):
    """Max pooling that also returns argmax positions (for unpool).
    reference: operators/max_pool_with_index_op."""
    helper = LayerHelper("max_pool2d_with_index", **locals())
    ks = [pool_size, pool_size] if isinstance(pool_size, int) else \
        list(pool_size)
    st = ks if pool_stride is None else (
        [pool_stride, pool_stride] if isinstance(pool_stride, int)
        else list(pool_stride))
    pd = [pool_padding, pool_padding] if isinstance(pool_padding, int) \
        else list(pool_padding)
    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    mask.stop_gradient = True
    helper.append_op(type="max_pool2d_with_index",
                     inputs={"X": [input]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"ksize": ks, "strides": st, "paddings": pd})
    return out, mask


def unpool(input, indices, unpool_size=None, pool_size=2, pool_stride=None,
           pool_padding=0, name=None):
    """Max unpooling using indices from max_pool2d_with_index. Pass either
    unpool_size or the pooling geometry that produced the indices.
    reference: operators/unpool_op.cc."""
    helper = LayerHelper("unpool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    ks = [pool_size, pool_size] if isinstance(pool_size, int) else \
        list(pool_size)
    st = ks if pool_stride is None else (
        [pool_stride, pool_stride] if isinstance(pool_stride, int)
        else list(pool_stride))
    pd = [pool_padding, pool_padding] if isinstance(pool_padding, int) \
        else list(pool_padding)
    attrs = {"ksize": ks, "strides": st, "paddings": pd}
    if unpool_size is not None:
        attrs["unpooled_size"] = list(unpool_size)
    helper.append_op(type="unpool",
                     inputs={"X": [input], "Indices": [indices]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def mdlstm(input, size, param_attr=None, bias_attr=None, name=None):
    """2-D grid LSTM: each cell conditions on the left and up neighbors.
    input: [N, H, W, C] -> out [N, H, W, size].
    reference: gserver/layers/MDLstmLayer.cpp."""
    helper = LayerHelper("mdlstm", **locals())
    dtype = helper.input_dtype()
    c = input.shape[-1]
    wx = helper.create_parameter(helper.param_attr, shape=[c, 5 * size],
                                 dtype=dtype)
    wl = helper.create_parameter(ParamAttr(), shape=[size, 5 * size],
                                 dtype=dtype)
    wu = helper.create_parameter(ParamAttr(), shape=[size, 5 * size],
                                 dtype=dtype)
    b = None
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[5 * size], dtype=dtype,
                                    is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape[:-1]) + (size,)
    helper.append_op(type="mdlstm",
                     inputs={"X": [input], "WeightX": [wx],
                             "WeightL": [wl], "WeightU": [wu],
                             "Bias": [b] if b is not None else []},
                     outputs={"Out": [out]})
    return out
