"""Tensor-creation/manipulation layers.

reference: python/paddle/fluid/layers/tensor.py (create_tensor, cast, concat,
sums, assign, fill_constant, ones, zeros, argmax/argmin...).
"""
from __future__ import annotations

import numpy as np

from ..core import ir
from ..core.types import convert_dtype
from .layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "assign", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "argmax", "argmin",
    "reverse", "increment", "autoincreased_step_counter",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr
    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr.to_attr(attr) if attr else ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..initializer import ConstantInitializer
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(dtype=dtype, shape=shape,
                                        persistable=persistable,
                                        name=name)
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = x.shape
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": str(x.dtype),
                            "out_dtype": str(convert_dtype(dtype))})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype())
    helper.append_op(type="concat", inputs={"X": input},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=helper.input_dtype())
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, ir.Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    else:
        value = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=str(value.dtype))
        helper.append_op(type="assign_value", outputs={"Out": [output]},
                         attrs={"shape": list(value.shape), "values": value,
                                "dtype": str(value.dtype)})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype":
                            str(convert_dtype(dtype)), "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": str(convert_dtype(dtype)),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="arg_max", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="arg_min", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis if isinstance(axis, (list, tuple))
                            else [axis]})
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 counter incremented by ``step`` once per
    executed run; the first observed value is ``begin``.

    Like the reference, the default name is the FIXED
    ``@STEP_COUNTER@`` and an existing counter is returned as-is (no
    second increment op), so every call site shares one global step —
    two increments per run would make LR schedules decay double-speed.
    reference: layers/tensor.py autoincreased_step_counter."""
    from ..initializer import ConstantInitializer
    name = counter_name or "@STEP_COUNTER@"
    block = ir.default_main_program().global_block()
    if block.has_var(name):
        return block.var(name)
    helper = LayerHelper("global_step_counter")
    counter = helper.create_global_variable(
        name=name, shape=(1,), dtype="int64", persistable=True)
    helper.set_variable_initializer(
        counter, ConstantInitializer(begin - step))
    increment(counter, value=step, in_place=True)
    counter.stop_gradient = True
    return counter


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out
