"""Operator sugar on Variables (reference: python/paddle/fluid/layers/
math_op_patch.py — monkey-patches Variable with __add__ etc.)."""
from __future__ import annotations

from ..core import ir


def binary(x, other, op, reverse=False):
    prog = x.block.program
    if prog is not ir.default_main_program():
        # ops on vars of a non-default program must land in that program
        old = ir.switch_main_program(prog)
        try:
            return _binary(x, other, op, reverse)
        finally:
            ir.switch_main_program(old)
    return _binary(x, other, op, reverse)


def _binary(x, other, op, reverse=False):
    from .layer_helper import LayerHelper
    helper = LayerHelper(op)
    if isinstance(other, (int, float)):
        if op == "elementwise_add":
            return _scale(helper, x, 1.0, float(other))
        if op == "elementwise_sub":
            if reverse:
                return _scale(helper, x, -1.0, float(other))
            return _scale(helper, x, 1.0, -float(other))
        if op == "elementwise_mul":
            return _scale(helper, x, float(other), 0.0)
        if op == "elementwise_div" and not reverse:
            return _scale(helper, x, 1.0 / float(other), 0.0)
        # build a constant tensor for the general case
        const = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(type="fill_constant", outputs={"Out": [const]},
                         attrs={"shape": list(x.shape or (1,)),
                                "value": float(other),
                                "dtype": str(x.dtype)})
        other = const
    a, b = (other, x) if reverse else (x, other)
    dtype = "bool" if op in ("less_than", "less_equal", "greater_than",
                             "greater_equal", "equal", "not_equal") else x.dtype
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = x.shape
    helper.append_op(type=op, inputs={"X": [a], "Y": [b]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def _scale(helper, x, scale, bias):
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": scale, "bias": bias,
                            "bias_after_scale": True})
    return out
