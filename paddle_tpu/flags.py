"""Process-level flag registry: the gflags role.

The reference configures its runtime through three generations of gflags
(legacy set: reference paddle/utils/Flags.cpp:18-95 — use_gpu,
trainer_count, port, trainer_id…; fluid's own: FLAGS_benchmark,
FLAGS_check_nan_inf in framework/executor.cc:29-32, dynload dirs in
platform/dynload/dynamic_loader.cc:25-44) re-exported to Python via
``core.init_gflags`` (pybind.cc). This module is the TPU-native analog:
a typed, declared-with-default registry, overridable three ways —

- environment: ``PADDLE_TPU_FLAGS="check_nan_inf=true,conv_impl=matmul"``
  or per-flag ``PADDLE_TPU_FLAG_CHECK_NAN_INF=true`` (read at first use);
- code: ``flags.FLAGS.check_nan_inf = True`` or ``flags.set_flags({...})``;
- CLI: ``init_from_args(argv)`` consumes ``--name=value`` pairs and returns
  the rest (the InitGflags role, reference: framework/init.cc:25).

Declaring is ``DEFINE_bool/int32/float/string(name, default, help)``;
reading is attribute access on ``FLAGS``. Unknown names raise — the same
contract as gflags' compile-time check.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List

__all__ = ["FLAGS", "DEFINE_bool", "DEFINE_int32", "DEFINE_float",
           "DEFINE_string", "set_flags", "get_flags", "init_from_args",
           "flags_guard"]

_TRUE = frozenset(("1", "true", "yes", "on"))
_FALSE = frozenset(("0", "false", "no", "off", ""))


def _parse_bool(s):
    if isinstance(s, bool):
        return s
    t = str(s).strip().lower()
    if t in _TRUE:
        return True
    if t in _FALSE:
        return False
    raise ValueError("not a boolean: %r" % (s,))


class _FlagDef(object):
    __slots__ = ("name", "default", "help", "parse")

    def __init__(self, name, default, help_, parse):
        self.name = name
        self.default = default
        self.help = help_
        self.parse = parse


class _Flags(object):
    """Attribute-style access over the registry; thread-safe writes."""

    def __init__(self):
        object.__setattr__(self, "_defs", {})
        object.__setattr__(self, "_values", {})
        object.__setattr__(self, "_lock", threading.Lock())
        object.__setattr__(self, "_env_loaded", False)

    # -- registry ----------------------------------------------------------
    def _define(self, name, default, help_, parse):
        with self._lock:
            if name in self._defs:
                raise ValueError("flag %r already defined" % name)
            self._defs[name] = _FlagDef(name, default, help_, parse)

    def _load_env_once(self):
        if self._env_loaded:
            return
        with self._lock:
            if self._env_loaded:
                return
            blob = os.environ.get("PADDLE_TPU_FLAGS", "")
            for pair in blob.split(","):
                pair = pair.strip()
                if not pair:
                    continue
                k, _, v = pair.partition("=")
                d = self._defs.get(k.strip())
                if d is not None:
                    self._values[d.name] = d.parse(v.strip())
            for name, d in self._defs.items():
                env_key = "PADDLE_TPU_FLAG_" + name.upper()
                if env_key in os.environ:
                    self._values[name] = d.parse(os.environ[env_key])
            object.__setattr__(self, "_env_loaded", True)

    # -- access ------------------------------------------------------------
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        self._load_env_once()
        if name not in self._defs:
            raise AttributeError("undeclared flag %r" % name)
        return self._values.get(name, self._defs[name].default)

    def __setattr__(self, name, value):
        self._load_env_once()
        if name not in self._defs:
            raise AttributeError("undeclared flag %r" % name)
        with self._lock:
            self._values[name] = self._defs[name].parse(value)

    def _snapshot(self) -> Dict[str, Any]:
        self._load_env_once()
        return {n: self._values.get(n, d.default)
                for n, d in self._defs.items()}


FLAGS = _Flags()


def DEFINE_bool(name, default, help=""):
    FLAGS._define(name, default, help, _parse_bool)


def DEFINE_int32(name, default, help=""):
    FLAGS._define(name, default, help, int)


def DEFINE_float(name, default, help=""):
    FLAGS._define(name, default, help, float)


def DEFINE_string(name, default, help=""):
    FLAGS._define(name, default, help, str)


def set_flags(d: Dict[str, Any]):
    for k, v in d.items():
        setattr(FLAGS, k, v)


def get_flags(names=None) -> Dict[str, Any]:
    snap = FLAGS._snapshot()
    if names is None:
        return snap
    return {n: snap[n] for n in names}


def init_from_args(argv: List[str]) -> List[str]:
    """Consume ``--flag=value`` / ``--flag value`` pairs for declared flags;
    returns the remaining argv (unknown args pass through untouched)."""
    rest, i = [], 0
    FLAGS._load_env_once()
    while i < len(argv):
        a = argv[i]
        if a.startswith("--"):
            k, eq, v = a[2:].partition("=")
            if k in FLAGS._defs:
                if not eq:
                    if i + 1 >= len(argv):
                        raise ValueError("flag --%s needs a value" % k)
                    v, i = argv[i + 1], i + 1
                setattr(FLAGS, k, v)
                i += 1
                continue
        rest.append(a)
        i += 1
    return rest


class flags_guard(object):
    """Scoped overrides: ``with flags_guard(check_nan_inf=True): ...``."""

    def __init__(self, **over):
        self._over = over
        self._saved = {}

    def __enter__(self):
        for k, v in self._over.items():
            self._saved[k] = getattr(FLAGS, k)
            setattr(FLAGS, k, v)
        return FLAGS

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            setattr(FLAGS, k, v)
        return False


# ---------------------------------------------------------------------------
# Core flag set (the FLAGS_* the rest of the framework consults; the legacy
# trainer flags live on their consumers' call signatures instead)

DEFINE_bool("check_nan_inf", False,
            "scan every op output for NaN/Inf on the per-op path "
            "(reference: FLAGS_check_nan_inf, executor.cc:30)")
DEFINE_bool("benchmark", False,
            "synchronise and time every Executor.run "
            "(reference: FLAGS_benchmark, executor.cc:29)")
DEFINE_string("conv_impl", "conv",
              "dense conv2d lowering: 'conv' (lax.conv) or 'matmul' "
              "(shifted einsums); bench.py autotunes this on device")
DEFINE_string("conv_layout", "nchw",
              "internal conv execution layout: 'nchw' (the API contract "
              "layout, passed through) or 'nhwc' (transpose to NHWC/HWIO "
              "around the conv — TPU vector lanes ride the channel dim; "
              "XLA cancels the transpose pairs between adjacent convs); "
              "bench.py autotunes this on device")
DEFINE_bool("conv_first_s2d", False,
            "rewrite the ImageNet stem conv (7x7/s2/p3, C_in<=4) as "
            "space-to-depth + 4x4/s1 conv: 4x better MXU lane utilization "
            "on the 3-channel input (the public MLPerf ResNet trick); "
            "numerically exact, autotuned by bench.py")
DEFINE_bool("debug_shapes", False,
            "raise (instead of recording) on shape-inference failures")
DEFINE_bool("verify", False,
            "run the paddle_tpu.analysis static verifier on every program "
            "before its first trace (also enabled by PADDLE_TPU_VERIFY=1); "
            "malformed programs raise ProgramVerifyError with the full "
            "PT-code diagnostic list instead of a cryptic trace error. "
            "When the Executor takes the explicit-collective path this "
            "also runs the PT020-PT023 collective-consistency pass over "
            "the traced grad set, and every fresh compile runs the "
            "static memory preflight (analysis.memory, PT030): a "
            "program whose predicted peak HBM exceeds the budget "
            "raises with the residency table BEFORE the XLA compile "
            "instead of dying in an unreadable device OOM. Programs "
            "carrying declared PartitionSpecs (program._shardings) also "
            "run the static sharding preflight (analysis.sharding, "
            "PT040-PT045): invalid or conflicting specs raise with the "
            "sharding plan table before the jit compile")
DEFINE_float("memory_budget_gb", 0.0,
             "per-device HBM budget (GiB) the static memory planner "
             "checks predicted peaks against (lint --memory, the "
             "executor preflight under PADDLE_TPU_VERIFY, the elastic "
             "post-resize audit, PT034 KV-pool sizing). 0 = autodetect "
             "from device.memory_stats()['bytes_limit'] (present on "
             "TPU; usually absent on CPU, where the checks then stay "
             "silent). The CLI --budget-gb overrides per run. The "
             "estimate is static — it ignores XLA fusion/remat and "
             "allocator fragmentation, so a predicted fit is a lower "
             "bound, not a guarantee (doc/diagnostics.md)")
DEFINE_string("sanitize", "",
              "runtime sanitizer modes, comma-separated (union with the "
              "PADDLE_TPU_SANITIZE env var): 'alias' arms the "
              "donation-aliasing checks at the device-transfer choke "
              "points (executor state ingestion, checkpoint restore, "
              "serving KV-pool install) — a numpy-backed buffer headed "
              "for a donated argument position raises SanitizeError "
              "naming the var and entry point; 'locks' swaps the shared "
              "lock constructor (analysis.locks) for instrumented locks "
              "that record the acquisition-order graph and report "
              "cycles (potential deadlocks) and held-across-join "
              "hazards at process exit. Both cost nothing when off; "
              "honest limit: CPU CI can only observe the ORDER "
              "inversion, never the deadlock itself (doc/diagnostics.md)")
DEFINE_string("data_home", "~/.cache/paddle_tpu/dataset",
              "dataset cache directory (reference: v2/dataset common)")
DEFINE_int32("log_period", 100,
             "steps between trainer progress lines "
             "(reference: utils/Flags.cpp log_period)")
DEFINE_string("lstm_impl", "scan",
              "whole-sequence LSTM lowering: 'scan' (lax.scan) or "
              "'pallas' (fused VMEM-resident kernel, standard gate set)")
DEFINE_bool("pipeline", False,
            "default Trainer.train execution mode: True overlaps host feed "
            "prep (DataFeeder.feed + device_put) of batch k+1 with the "
            "device computing batch k and defers fetch materialization to "
            "real sync points (paddle_tpu.pipeline; per-call override via "
            "Trainer.train(pipeline=...)). Losses are bit-identical to the "
            "synchronous mode; check_nan_inf forces synchronous")
DEFINE_int32("pipeline_depth", 2,
             "bounded ring of device-resident prefetched feed buffers the "
             "async pipeline keeps in flight (2 = classic double "
             "buffering; <1 disables pipelining)")
DEFINE_bool("compile_cache", True,
            "persist XLA compilations to compile_cache_dir via jax's "
            "on-disk compilation cache so repeat runs skip the cold "
            "compile (~29 s/step-class for big programs); set to 0 to "
            "opt out. Never overrides an explicitly configured "
            "JAX_COMPILATION_CACHE_DIR")
DEFINE_string("compile_cache_dir", "~/.cache/paddle_tpu/xla",
              "directory for the persistent XLA compilation cache "
              "(used when FLAGS.compile_cache is on)")
DEFINE_int32("serve_max_batch", 8,
             "online serving (paddle_tpu.serving): most requests the "
             "micro-batcher coalesces into one run_many device dispatch. "
             "Also sets the padding buckets (powers of two capped here) "
             "the model registry pre-compiles at warm-up, so raising it "
             "on a live service only takes effect for models (re)loaded "
             "afterwards")
DEFINE_float("serve_batch_timeout_ms", 2.0,
             "online serving: how long the dispatch loop holds the "
             "OLDEST queued request open for same-model arrivals before "
             "dispatching a partial batch — the latency/throughput "
             "knob: 0 dispatches immediately (lowest latency, occupancy "
             "only from true concurrency); larger values trade p50 "
             "latency for fuller batches")
DEFINE_string("comm_policy", "none",
              "gradient-communication policy for the DP sync path "
              "(paddle_tpu.comm): 'none' = per-parameter pmean, "
              "bit-identical to the pre-comm psum path; 'fused' = "
              "bucketed (comm_bucket_mb) single all-reduce per bucket — "
              "N-param dispatches become N-bucket dispatches; "
              "'hierarchical' = bucketed + topology-routed: intra-host "
              "reduce-scatter -> inter-host ring on 1/chips of the "
              "bytes -> intra-host all-gather (the slow inter-host wire "
              "carries 1/chips of the flat-ring traffic). Policy matrix "
              "and when each wins: doc/comm.md")
DEFINE_float("comm_bucket_mb", 4.0,
             "bucket size bound in MiB for the fused/hierarchical/int8 "
             "comm policies: grad leaves are concatenated, in "
             "declaration order and per dtype, into flat buckets of at "
             "most this many payload bytes (a larger leaf gets its own "
             "bucket). Bigger buckets amortise dispatch latency; "
             "smaller ones overlap earlier with the backward pass")
DEFINE_string("comm_quant", "none",
              "wire precision for the comm policies: 'none' (fp32) or "
              "'int8' (symmetric per-chunk quantisation with fp32 "
              "scales + error-feedback residuals carried in comm state, "
              "EQuARX-style). With comm_policy=hierarchical only the "
              "inter-host leg quantises (stateless); otherwise the "
              "policy promotes to fused buckets. Dynamic-range overflow "
              "falls back to full precision for that step with a "
              "recorded comm_degraded event")
DEFINE_int32("comm_hosts", 0,
             "host count of the (host, chip) factorisation the "
             "hierarchical comm policy routes along; 0 = auto "
             "(jax.process_count() when it divides the data axis, else "
             "flat). Set explicitly to simulate a multi-host topology "
             "on a forced CPU mesh (tools/comm_smoke.py uses 2x4)")
DEFINE_bool("comm_overlap", False,
            "overlap gradient communication with the tail of backward "
            "(paddle_tpu.comm.overlap): the DP step builders issue each "
            "comm bucket's all-reduce in backward-finalisation order, as "
            "its own data-independent collective, and apply that "
            "bucket's parameter update immediately — no bucket waits on "
            "another's collective, so XLA's latency-hiding scheduler "
            "can hide the early buckets behind the remaining backward "
            "chain. 0 (default) keeps the serialized sync-then-update "
            "step, bit-identical to the pre-overlap build. A raise at "
            "fault site comm.overlap degrades to the serialized path "
            "with a recorded comm_degraded event")
DEFINE_float("comm_split_ratio", 0.75,
             "fraction of each large bucket the multipath comm policy "
             "(comm_policy=multipath, FlexLink-style) routes over the "
             "PRIMARY path (flat ring over ICI); the remainder rides "
             "the SECONDARY path (hierarchical inter-host hop over the "
             "comm_hosts factorisation) at the same time, so both "
             "fabrics carry bytes simultaneously. Configure from "
             "measured per-path bandwidths via "
             "comm.measured_split_ratio(primary_gbps, secondary_gbps); "
             "buckets below 64 KiB ride the primary path whole "
             "(splitting them buys nothing and costs a dispatch)")
DEFINE_bool("comm_gspmd", True,
            "route the GSPMD Executor path's data-parallel gradient "
            "sync through the explicit paddle_tpu.comm collectives "
            "(bucketed/hierarchical/quantized per comm_policy) instead "
            "of only modelling the bytes: eligible pure-DP programs "
            "trace under shard_map with comm.all_reduce_grads at the "
            "backward/optimizer boundary, and Executor.stats reports "
            "comm_path='explicit' with stats measured from the traced "
            "plan. Only engages when comm_policy != 'none' (the none "
            "policy keeps the pre-PR GSPMD build bit-identical); "
            "ineligible programs (tensor/ZeRO sharding, batch-coupled "
            "or random ops, non-batch fetches) fall back to the "
            "modelled path with a recorded comm_degraded event. 0 "
            "forces model-only")
DEFINE_bool("tune", True,
            "consult the paddle_tpu.tune winner cache at kernel dispatch "
            "sites: a cached per-(device, shape) winner activates the "
            "Pallas kernel with the winning config (tune_hits); a miss "
            "keeps legacy behavior — the kernel's default config where a "
            "kernel is already flag-enabled (tune_misses), stock XLA "
            "lowering otherwise (tune_fallbacks). 0 disables cache "
            "consultation entirely: dispatch is exactly the pre-tune "
            "build, with fallbacks still counted so the stats say why "
            "nothing was tuned")
DEFINE_string("tune_cache_dir", "~/.cache/paddle_tpu/tune",
              "directory of the persistent kernel-winner cache "
              "(winners.json keyed device_kind|kernel|shape-signature, "
              "entry-CRC checked; written by `paddle_tpu tune` and "
              "tune.autotune) — deliberately beside compile_cache_dir: "
              "both are per-device derived state, safe to wipe")
DEFINE_int32("tune_budget", 0,
             "cap on candidates the autotune loop compiles+times per "
             "(kernel, shape), stock-XLA rung included; 0 = the full "
             "valid space. The CLI's --budget overrides per run")
DEFINE_bool("elastic", False,
            "default supervision mode for paddle_tpu.launch: True turns "
            "the launcher's fail-fast job abort into survive-and-resize "
            "(paddle_tpu.elastic) — on worker death the supervisor "
            "classifies the loss (signal death = permanent, crash exit = "
            "transient while the restart budget lasts), re-queues the "
            "dead worker's leased dataset tasks through the task master, "
            "re-plans the (host, chip) comm factorisation for the "
            "survivor set, and relaunches the job on the survivors from "
            "load_latest + the paired task-master snapshot, recording an "
            "elastic_resize event — the job only dies when the quorum "
            "(elastic_min_workers) is gone. CLI --elastic overrides")
DEFINE_int32("elastic_min_workers", 1,
             "elastic quorum: the smallest world size the supervisor "
             "will resize down to; one more permanent worker loss below "
             "this aborts the job with the real exit code (CLI "
             "--elastic-min-workers overrides)")
DEFINE_int32("elastic_restart_budget", 2,
             "how many transient worker failures (non-zero exit, not "
             "signal death) the elastic supervisor restarts at FULL "
             "world size before treating the next one as permanent; "
             "restarts back off on the resilience RetryPolicy schedule "
             "(CLI --elastic-restart-budget overrides)")
DEFINE_float("step_timeout_s", 0.0,
             "per-step deadline for the Trainer loop's hang watchdog "
             "(paddle_tpu.resilience.watchdog). 0 (default) = off. When "
             "set, a monitor thread checks that the training loop makes "
             "progress (every batch and every declared materialization "
             "point re-arms the deadline); a step that exceeds it — a "
             "wedged collective, a stalled reader, a hung device — "
             "records a durable step_hung event, dumps the profiler "
             "timeline artifact next to the elastic state dir, and "
             "exits the worker with code 75 (EX_TEMPFAIL) so an elastic "
             "supervisor classifies the death as TRANSIENT and "
             "restarts it from the paired checkpoint: a hang becomes a "
             "restart, never a wedged gang. Size it to several times "
             "the slowest legitimate step (cold compiles re-arm the "
             "deadline only when they finish)")
DEFINE_float("loss_spike_factor", 0.0,
             "numeric guardrail (paddle_tpu.resilience.guardrails): a "
             "batch whose loss exceeds this factor times the running "
             "median of recent accepted losses is treated like a "
             "non-finite loss — the batch is SKIPPED (not counted into "
             "pass metrics, recorded as a batch_skipped event) under "
             "the loss_skip_budget. 0 (default) = spike detection off "
             "(non-finite detection is governed by loss_skip_budget "
             "alone). The comparison starts after 3 accepted batches; "
             "values below ~2 will false-positive on normal early-"
             "training noise")
DEFINE_int32("loss_skip_budget", 0,
             "numeric guardrail: how many CONSECUTIVE batches the "
             "Trainer loop may skip (non-finite loss, or a spike past "
             "loss_spike_factor) before escalating. 0 (default) = "
             "guardrails off — a non-finite loss flows through exactly "
             "as before (check_nan_inf keeps its per-op raise "
             "semantics). On budget exhaustion the loop REWINDS model "
             "+ optimizer state to the last checkpoint (the PAIRED "
             "checkpoint in elastic mode) once per budget window and "
             "keeps training; a second consecutive exhaustion with no "
             "accepted batch in between gives up with "
             "FloatingPointError. Each skip forces a per-batch loss "
             "materialization — under pipeline=True the guardrail "
             "check is a declared sync point")
DEFINE_int32("elastic_ckpt_period", 1,
             "elastic Trainer worker (Trainer.train(elastic=True)): "
             "lease-committed batches between paired checkpoint+"
             "task-master-snapshot saves. 1 (default) pairs every "
             "committed batch — the chaos-gate setting; larger values "
             "amortise checkpoint cost, and a kill then replays up to "
             "period-1 committed tasks from the paired snapshot "
             "(still exactly-once in the resumed timeline: the model "
             "rolls back to the same point the task master does). A "
             "numeric-guardrail REWIND, by contrast, cannot roll the "
             "live master back, so at period>1 it discards up to "
             "period-1 accepted batches' contributions with a recorded "
             "guard_rewind_dropped_commits event — run period=1 when "
             "every contribution must survive a rewind")
DEFINE_int32("serve_queue_depth", 64,
             "online serving: bound on requests queued for dispatch "
             "across all models; request queue_depth+1 is shed "
             "immediately with OverloadError (HTTP 429) and a recorded "
             "request_shed degradation event instead of queuing into "
             "certain lateness")
DEFINE_int32("serve_max_running", 8,
             "generation engine (paddle_tpu.serving.generator): most "
             "sequences decoded concurrently by the fused iteration-"
             "level decode step. Fixes the decode program's batch "
             "shape, so it is compiled ONCE per engine — raising it on "
             "a live engine has no effect; set it before the engine is "
             "built. Idle rows cost one masked lane each, so size it "
             "to the sustained concurrency, not the peak queue")
DEFINE_int32("serve_kv_pages", 64,
             "generation engine: usable pages preallocated in the "
             "per-model paged KV pool (one extra trash page is added "
             "internally). Pool token capacity = serve_kv_pages x "
             "serve_page_tokens; admission reserves ceil((prompt + "
             "max_new_tokens) / serve_page_tokens) pages per sequence, "
             "and a request that could NEVER fit is shed at submit "
             "with a recorded kv_pool_exhausted event")
DEFINE_int32("serve_page_tokens", 16,
             "generation engine: K/V positions per page. Smaller pages "
             "waste less tail capacity per sequence but grow the block "
             "tables (max_blocks = ceil(max_seq / page_tokens) gather "
             "indices per row in the fused decode step)")
DEFINE_bool("serve_device_sample", True,
            "generation engine: sample the next token INSIDE the jitted "
            "decode/prefill step (seeded jax.random.categorical keyed "
            "by fold_in(PRNGKey(seed), token_offset); temperature<=0 is "
            "argmax) so each step returns [R] tokens + logprobs instead "
            "of [R, V] logits and the host loop is pure bookkeeping. "
            "Greedy output is token-identical to host sampling; "
            "temperature output is a DIFFERENT (but seeded, "
            "reproducible) stream than the host RandomState path. 0 "
            "restores host-side sampling bit-identically; a fused build "
            "failure degrades to the same host path with a recorded "
            "device_sample_degraded event (fault site serving.sample). "
            "Resolved once at engine construction — flipping it needs "
            "a new engine (hot reload)")
DEFINE_string("serve_draft_dir", "",
              "generation engine: directory of an exported generative "
              "artifact to load as the DRAFT model for speculative "
              "decoding (same vocabulary as the target; typically much "
              "smaller). Empty disables speculation unless the serving "
              "artifact itself is a paired speculative export "
              "(inference.export_speculative), which carries its own "
              "draft and wins. The draft gets its own KV page pool "
              "sized by serve_kv_pages x serve_page_tokens, priced into "
              "the PT034 memory check alongside the target's")
DEFINE_int32("serve_spec_k", 4,
             "generation engine: speculation depth — how many tokens "
             "the draft model proposes per round before ONE fused "
             "target step verifies them all. Per-request spec_k can "
             "only lower it. Greedy output is token-identical to "
             "non-speculative decode at any k; higher k wins only "
             "while the draft's acceptance rate holds up (watch "
             "acceptance_rate in /statz). 0 disables speculation even "
             "when a draft is available")
DEFINE_bool("serve_prefix_sharing", False,
            "generation engine: content-hash prefill pages (rolling "
            "blake2b chain over serve_page_tokens-sized token chunks) "
            "and let N concurrent requests PIN one physical copy of a "
            "shared prompt prefix instead of each paying full-price KV "
            "pages. The pool refcounts pages; the first divergent "
            "write copy-on-writes just that page; admission discounts "
            "its reservation by the cached full pages it will pin; an "
            "LRU keeps unreferenced prefix pages warm until allocation "
            "pressure reclaims them. Greedy output is bit-identical "
            "with sharing on or off. A failure in the sharing layer "
            "degrades that engine to plain private pages with a "
            "recorded prefix_degraded event (fault site "
            "serving.prefix), never an outage")
DEFINE_string("serve_tier", "",
              "serving tier class for the disaggregated fleet "
              "(serving/disagg.py): empty = a normal do-everything "
              "replica; 'prefill' advertises the replica as prefill-"
              "class (router sends it fresh prompts, ships the "
              "finished KV pages + request state to a decode replica); "
              "'decode' advertises decode-class (receives handoff "
              "artifacts, runs the steady-state token loop). The tier "
              "is advertised through /statz; the Router never "
              "dispatches a tier to work outside its class")
DEFINE_float("route_prefill_up_queue", 4.0,
             "tiered autoscale: a prefill-class tier scales UP when "
             "its per-replica mean queue depth (queued + running "
             "prefills — the compute-bound signal) exceeds this; see "
             "route_scale_down_pressure's decode analogue "
             "route_decode_up_frac for the decode tier")
DEFINE_float("route_decode_up_frac", 0.8,
             "tiered autoscale: a decode-class tier scales UP when its "
             "mean KV page-pool PHYSICAL occupancy fraction exceeds "
             "this (memory-bound signal — decode replicas run out of "
             "pages long before they run out of FLOPs)")
DEFINE_int32("route_replicas", 3,
             "serving router (paddle_tpu.serving.router): how many "
             "`serve` worker processes the replica pool spawns and "
             "supervises behind one `paddle_tpu route` front tier "
             "(CLI --replicas overrides)")
DEFINE_int32("route_poll_ms", 100,
             "serving router: background poll interval for each "
             "replica's /statz (load score) and /healthz (liveness). "
             "Between polls the score is freshened by the router's own "
             "in-flight request count, so a shorter interval mainly "
             "tightens eject/readmit latency, not balance")
DEFINE_int32("route_eject_after", 3,
             "serving router: consecutive /healthz failures before a "
             "replica is ejected from routing (it keeps being polled; "
             "see route_readmit_after for the probation readmit)")
DEFINE_int32("route_readmit_after", 2,
             "serving router: consecutive /healthz successes an "
             "ejected replica must bank (probation) before it is "
             "readmitted to routing — one lucky poll must not put a "
             "flapping replica back in rotation")
DEFINE_int32("route_restart_budget", 2,
             "serving router: how many times the replica pool restarts "
             "one dead `serve` worker (on the resilience RetryPolicy "
             "backoff schedule, each restart a recorded "
             "router_replica_restart event) before declaring it lost "
             "(router_replica_lost; the remaining replicas keep "
             "serving). The budget bounds crash LOOPS: a respawn that "
             "stays up 60s (ReplicaPool budget_reset_s) resets the "
             "slot's record")
DEFINE_float("route_proxy_timeout_s", 300.0,
             "serving router: socket timeout for one proxied replica "
             "request (predict/generate/reload). A request carrying "
             "deadline_ms uses min(deadline, this). Proxy failures "
             "inside the window fail over once to the next-best "
             "replica")
DEFINE_float("route_pressure_alpha", 0.4,
             "serving router: EWMA smoothing factor for the per-model "
             "autoscale pressure signal (smoothed = alpha*raw + "
             "(1-alpha)*previous, seeded with the first raw sample). "
             "/statz exposes both 'pressure' (raw, one poll window) "
             "and 'pressure_smoothed'; the autoscaler acts ONLY on the "
             "smoothed one, so a single poll spike can neither trigger "
             "a scale-up nor mask a sustained overload. Must be in "
             "(0, 1]; 1.0 disables smoothing")
DEFINE_float("route_scale_up_pressure", 1.0,
             "autoscaler (paddle_tpu.serving.autoscale): smoothed "
             "pressure at or above this for k_up consecutive control "
             "ticks grows the fleet by one replica (pressure = "
             "backlog/capacity + shed_rate, so 1.0 means the backlog "
             "equals the healthy fleet's capacity). Must exceed "
             "route_scale_down_pressure — the dead band between them "
             "is the hysteresis that stops oscillating load from "
             "thrashing the fleet")
DEFINE_float("route_scale_down_pressure", 0.2,
             "autoscaler: smoothed pressure at or below this for the "
             "(longer) quiet window shrinks the fleet by one replica, "
             "drain-first: the victim is marked draining in the "
             "router, in-flight requests run out (bounded by the drain "
             "deadline), then the worker is retired on the shared "
             "SIGTERM->SIGKILL escalation — no request is lost to a "
             "policy decision")
DEFINE_float("route_cooldown_s", 30.0,
             "autoscaler: minimum seconds between scale-UPs (the "
             "scale-down cooldown defaults to 2x this, and a "
             "scale-down additionally waits it out since the last "
             "scale-up). Cooldowns are the second flap guard after "
             "the threshold hysteresis")
DEFINE_float("gray_step_ratio", 0.0,
             "gray-failure detection for the elastic TRAINING gang "
             "(paddle_tpu.resilience.grayfail consumed by the elastic "
             "supervisor): a rank whose per-step wall time — published "
             "in its heartbeat-rank<N>.json under --state-dir — stays "
             "above ratio x the cross-rank median (median+MAD robust "
             "baseline, consecutive sweeps, hysteresis) is condemned "
             "as a GRAY failure: alive and heartbeating but "
             "consistently slower than its peers, dragging every "
             "collective to its pace. 0.0 (default) = detection off; "
             "enable with a ratio comfortably above legitimate skew "
             "(3.0 is the chaos-gate setting). Mitigation is budgeted "
             "by gray_mitigation_budget and recorded as durable "
             "gray_suspected / gray_mitigated events; it never drops "
             "the gang below --min-workers and runs at most one "
             "mitigation per generation. CPU caveat: the CI legs "
             "inject slowness via delay faults on trainer.step — real "
             "cross-host skew (thermal throttle, a bad NIC) needs the "
             "pod trip")
DEFINE_int32("gray_mitigation_budget", 1,
             "gray-failure mitigation budget for the elastic "
             "supervisor: how many condemned-rank mitigations are "
             "spent as TRANSIENT restarts (full-world relaunch from "
             "the paired checkpoint — maybe the host just had a bad "
             "hour) before a recurrence is demoted to PERMANENT: the "
             "condemned rank is dropped and the gang resizes via the "
             "normal clean-resize machinery. Spent per job, not per "
             "generation, so a persistently slow host cannot buy "
             "itself a restart loop")
DEFINE_float("route_gray_ratio", 0.0,
             "gray-failure detection for the SERVING fleet "
             "(paddle_tpu.resilience.grayfail consumed by the "
             "router's poller): a replica whose proxied-latency EWMA "
             "stays above ratio x the cross-replica median (same "
             "robust baseline + streak + hysteresis detector as the "
             "training tier) is drained and ejected into the normal "
             "probation/readmit cycle EVEN THOUGH its /healthz still "
             "answers 200 — latency-only ejection, recorded as "
             "durable gray_suspected / gray_mitigated events and "
             "counted in /statz. 0.0 (default) = detection off; 3.0 "
             "is the load_bench slow-replica-leg setting. Needs at "
             "least 3 replicas with traffic to pick an outlier (the "
             "median of a pair splits it)")
DEFINE_float("route_gray_hold_s", 10.0,
             "serving router: how long a latency-ejected (gray) "
             "replica is held out of rotation before its detector "
             "record is forgotten and the normal /healthz probation "
             "(route_readmit_after) may readmit it. An ejected "
             "replica receives no traffic, so its latency signal "
             "cannot clear itself — the hold is the readmit path, and "
             "a replica that is still slow after readmission is "
             "simply condemned again")
DEFINE_float("route_hedge_budget", 0.0,
             "serving router: request hedging for IDEMPOTENT "
             ":predict proxies only (:generate consumes KV budget and "
             "decode slots — it is NEVER hedged). A predict still "
             "unanswered past the hedge deadline — the router's "
             "observed p99 proxied latency, floored at "
             "route_hedge_min_ms — fires ONE hedged attempt at the "
             "next-best replica; the first answer wins and the loser "
             "is discarded on arrival. This value caps hedges as a "
             "fraction of proxied traffic (0.05 = at most 5% extra "
             "load) so tail-chasing can never melt an overloaded "
             "fleet. 0.0 (default) = hedging off. Hedges and hedge "
             "wins are counted in /statz and the grayfail profiler "
             "family")
DEFINE_float("route_hedge_min_ms", 20.0,
             "serving router: floor for the p99-derived hedge "
             "deadline, and the deadline used while fewer than 20 "
             "latency samples exist. Keeps a fast fleet (p99 of a "
             "few ms) from hedging on scheduler noise — a hedge "
             "should chase a genuinely late request, not jitter")
