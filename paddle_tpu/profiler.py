"""Profiling: per-run host timers + XLA/xplane trace capture.

reference: python/paddle/fluid/profiler.py:20-125 (profiler / cuda_profiler
context managers over the C++ RecordEvent profiler,
paddle/fluid/platform/profiler.h:60-151) and platform/device_tracer.h (CUPTI
timeline). On TPU the per-op host loop doesn't exist — one jitted program is
one device launch — so the host profiler records per-run wall/compile times
per program, and the device timeline comes from jax.profiler's xplane trace
(TensorBoard-compatible), which is the CUPTI-tracer equivalent.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

__all__ = ["timer", "stat_summary", "print_stats", "reset_stats",
           "BarrierStat",
           "start_profiler", "stop_profiler", "reset_profiler", "profiler",
           "cuda_profiler", "xla_trace", "profiler_enabled", "record_run",
           "record_op_event", "record_program_analysis", "write_timeline",
           "update_pipeline_counters", "pipeline_counters",
           "reset_pipeline_counters",
           "update_serving_counters", "serving_counters",
           "reset_serving_counters",
           "update_comm_counters", "comm_counters", "reset_comm_counters",
           "update_tune_counters", "tune_counters", "reset_tune_counters",
           "update_elastic_counters", "elastic_counters",
           "reset_elastic_counters",
           "update_generation_counters", "generation_counters",
           "reset_generation_counters", "speculation_counters",
           "prefix_counters",
           "update_router_counters", "router_counters",
           "reset_router_counters",
           "update_autoscale_counters", "autoscale_counters",
           "reset_autoscale_counters",
           "update_memory_counters", "memory_counters",
           "reset_memory_counters",
           "update_trainer_counters", "trainer_counters",
           "reset_trainer_counters",
           "update_grayfail_counters", "grayfail_counters",
           "reset_grayfail_counters"]

_enabled = False
_records = defaultdict(list)  # label -> [seconds]
_op_events = []               # chrome-trace X events (eager per-op spans)
_program_analyses = {}        # label -> {flops, bytes, collectives, ...}
_pipeline_counters = defaultdict(float)  # async-pipeline observability
_serving_counters = defaultdict(float)   # online-serving observability
_comm_counters = defaultdict(float)      # gradient-communication observability
_tune_counters = defaultdict(float)      # kernel-autotuning observability
_elastic_counters = defaultdict(float)   # elasticity observability
_generation_counters = defaultdict(float)  # autoregressive-serving observability
_router_counters = defaultdict(float)     # multi-replica-router observability
_autoscale_counters = defaultdict(float)  # closed-loop-autoscaler observability
_memory_counters = defaultdict(float)     # static-memory-planner observability
_trainer_counters = defaultdict(float)    # trainer-loop failure-policy observability
_grayfail_counters = defaultdict(float)   # gray-failure-detection observability
_T0 = time.perf_counter()


def profiler_enabled():
    return _enabled


_phase = "eager"


def set_phase(phase):
    """'eager' = per-op spans are real run time; 'trace' = spans measure
    trace/lowering cost (the jit path runs as one fused program)."""
    global _phase
    _phase = phase


def record_run(label, seconds):
    """Called by Executor.run while profiling is on."""
    if _enabled:
        _records[label].append(seconds)
        t_end = time.perf_counter()
        _op_events.append({
            "name": label, "cat": "program", "ph": "X",
            "ts": (t_end - seconds - _T0) * 1e6, "dur": seconds * 1e6,
            "pid": 0, "tid": 1, "args": {}})


def start_profiler(state="All"):
    """reference: profiler.py start_profiler (state CPU/GPU/All — moot on
    TPU: the device timeline needs xla_trace instead)."""
    global _enabled
    _enabled = True


def reset_profiler():
    _records.clear()
    del _op_events[:]
    _program_analyses.clear()
    _pipeline_counters.clear()
    _serving_counters.clear()
    _comm_counters.clear()
    _tune_counters.clear()
    _elastic_counters.clear()
    _generation_counters.clear()
    _router_counters.clear()
    _autoscale_counters.clear()
    _memory_counters.clear()
    _trainer_counters.clear()
    _grayfail_counters.clear()


def update_pipeline_counters(**counters):
    """Accumulate async-pipeline observability counters (always on — a
    few dict adds per pass/materialisation, not per op). Keys in use:
    ``feed_wait_ms``, ``dispatch_depth`` (kept as a max, not a sum),
    ``fetch_sync_count``, ``compile_cache_hits``, ``pipeline_batches``,
    ``slot_reuse``, ``fallback_sync``."""
    for k, v in counters.items():
        if k == "dispatch_depth":
            _pipeline_counters[k] = max(_pipeline_counters[k], float(v))
        else:
            _pipeline_counters[k] += float(v)


def pipeline_counters():
    """Snapshot {counter: value} of the async-pipeline counters."""
    return dict(_pipeline_counters)


def reset_pipeline_counters():
    _pipeline_counters.clear()


def update_serving_counters(**counters):
    """Accumulate online-serving observability counters (always on — a
    few dict adds per BATCH, not per request-row). Keys in use:
    ``requests``, ``batches``, ``padded_rows``, ``queue_wait_ms``,
    ``shed_overload``, ``shed_deadline``, ``failed``;
    ``max_occupancy`` is kept as a max, not a sum."""
    for k, v in counters.items():
        if k == "max_occupancy":
            _serving_counters[k] = max(_serving_counters[k], float(v))
        else:
            _serving_counters[k] += float(v)


def serving_counters():
    """Snapshot {counter: value} of the online-serving counters."""
    return dict(_serving_counters)


def reset_serving_counters():
    _serving_counters.clear()


def update_comm_counters(**counters):
    """Accumulate gradient-communication observability counters
    (paddle_tpu.comm; a few dict adds per step-BUILD or per recorded
    step, never per collective). Keys in use: ``comm_bytes`` (modelled
    per-chip wire bytes per step), ``comm_payload_bytes``,
    ``comm_buckets``, ``comm_dispatches``, ``comm_builds``; the overlap
    step (comm.overlap) adds ``comm_overlap_builds``,
    ``comm_overlap_buckets_early`` (buckets issued before the final
    one — each data-independent of the remaining backward chain) and
    ``comm_overlap_hidden_bytes_est`` (wire bytes of those early
    buckets — the estimate of what the latency-hiding scheduler can
    hide; an estimate, CPU CI cannot time a real fabric);
    ``comm_quant_fallbacks`` is a cumulative gauge kept as a max, not a
    sum (the comm state already accumulates it across steps)."""
    for k, v in counters.items():
        if k == "comm_quant_fallbacks":
            _comm_counters[k] = max(_comm_counters[k], float(v))
        else:
            _comm_counters[k] += float(v)


def comm_counters():
    """Snapshot {counter: value} of the gradient-communication counters."""
    return dict(_comm_counters)


def reset_comm_counters():
    _comm_counters.clear()


def update_tune_counters(**counters):
    """Accumulate kernel-autotuning observability counters
    (paddle_tpu.tune; a few dict adds per kernel DISPATCH, which happens
    at trace time — once per compile, never per step). Keys in use:
    ``tune_hits`` (cached winner applied), ``tune_misses`` (kernel ran
    its default config), ``tune_fallbacks`` (stock XLA lowering),
    ``tune_loops`` / ``tune_candidates`` (autotune-loop activity from
    the CLI / smoke gate)."""
    for k, v in counters.items():
        _tune_counters[k] += float(v)


def tune_counters():
    """Snapshot {counter: value} of the kernel-autotuning counters."""
    return dict(_tune_counters)


def reset_tune_counters():
    _tune_counters.clear()


def update_elastic_counters(**counters):
    """Accumulate elasticity observability counters (paddle_tpu.elastic;
    a few dict adds per RESIZE/RESUME — rare, operator-visible events,
    never per step). Keys in use: ``elastic_resizes`` (world shrinks),
    ``elastic_lost_ranks``, ``elastic_restarts`` (transient full-world
    relaunches), ``elastic_requeued_tasks`` (the dead worker's leased
    dataset tasks re-queued through the task master),
    ``elastic_resumes`` and ``elastic_resume_ms`` (cross-world
    checkpoint-restore latency), ``elastic_heartbeat_failures``."""
    for k, v in counters.items():
        _elastic_counters[k] += float(v)


def elastic_counters():
    """Snapshot {counter: value} of the elasticity counters."""
    return dict(_elastic_counters)


def reset_elastic_counters():
    _elastic_counters.clear()


def update_trainer_counters(**counters):
    """Accumulate trainer-loop failure-policy observability counters
    (the elastic-worker/watchdog/guardrail machinery; a few dict adds
    per SKIP/REWIND/HANG — operator-visible events, never per step).
    Keys in use: ``batches_skipped`` (numeric-guardrail skips),
    ``guard_rewinds`` (budget-exhaustion checkpoint rewinds),
    ``steps_hung`` (watchdog firings — normally the last counter the
    process ever bumps), ``elastic_tasks_committed`` and
    ``elastic_task_failures`` (lease accounting of the elastic Trainer
    worker), ``preempts_truncated`` (SIGTERM drains that could not fit
    a final checkpoint inside the grace window)."""
    for k, v in counters.items():
        _trainer_counters[k] += float(v)


def trainer_counters():
    """Snapshot {counter: value} of the trainer-loop counters."""
    return dict(_trainer_counters)


def reset_trainer_counters():
    _trainer_counters.clear()


_GEN_MAX_KEYS = frozenset(("gen_max_running", "gen_page_util_max"))


def update_generation_counters(**counters):
    """Accumulate autoregressive-serving observability counters
    (paddle_tpu.serving.generator; a few dict adds per engine STEP or
    per retired request, never per token-row). Keys in use:
    ``gen_requests``, ``gen_completed``, ``gen_prefills``,
    ``gen_decode_steps``, ``gen_tokens`` (generated, prompt excluded),
    ``gen_shed_overload`` / ``gen_shed_deadline`` / ``gen_shed_pool``,
    ``gen_preemptions``, ``gen_failed``;
    ``gen_device_sample_steps`` (decode steps whose sampling ran inside
    the jit), ``gen_host_logit_syncs`` (device edges that materialized
    a full logits row/batch on the host to sample — 0 on the fused
    path), ``gen_kernel_hits`` (decode steps routed through the Pallas
    paged-attention kernel); ``gen_max_running`` and
    ``gen_page_util_max`` are kept as maxima, not sums.

    Speculative decoding adds ``gen_spec_steps`` (decode steps that ran
    as draft-propose / fused-verify rounds), ``gen_draft_tokens``
    (tokens the draft proposed), ``gen_accepted_tokens`` (proposals the
    target's verify accepted — acceptance rate is their ratio, surfaced
    by :func:`speculation_counters`), and ``gen_spec_degraded``
    (speculation dropped to plain decode; fault site
    ``serving.speculate``).

    Prefix sharing and disaggregation add ``gen_prefix_hits`` (prefill
    pages satisfied from the shared cache instead of recomputed),
    ``gen_prefix_published`` (pages a prefill published for reuse),
    ``gen_cow_copies`` (copy-on-write page splits on first divergent
    write), ``gen_prefix_degraded`` (sharing dropped to private pages;
    fault site ``serving.prefix``), ``gen_handoff_installs`` (prefill
    artifacts installed on a decode replica), and ``gen_handoff_failed``
    (handoffs that fell back to re-prefill; fault site
    ``serving.ship``) — surfaced by :func:`prefix_counters`."""
    for k, v in counters.items():
        if k in _GEN_MAX_KEYS:
            _generation_counters[k] = max(_generation_counters[k], float(v))
        else:
            _generation_counters[k] += float(v)


def generation_counters():
    """Snapshot {counter: value} of the autoregressive-serving counters."""
    return dict(_generation_counters)


def speculation_counters():
    """The speculative-decoding slice of the generation counters, plus
    the derived ``acceptance_rate`` (accepted / drafted; 0.0 before any
    speculative round). This is the timeline artifact's ``speculation``
    section — all zeros on a non-speculative engine."""
    g = _generation_counters
    drafted = g.get("gen_draft_tokens", 0.0)
    return {
        "spec_steps": g.get("gen_spec_steps", 0.0),
        "draft_tokens": drafted,
        "accepted_tokens": g.get("gen_accepted_tokens", 0.0),
        "acceptance_rate": (g.get("gen_accepted_tokens", 0.0) / drafted
                            if drafted else 0.0),
        "spec_degraded": g.get("gen_spec_degraded", 0.0),
    }


def prefix_counters():
    """The prefix-sharing / disaggregation slice of the generation
    counters, plus the derived ``hit_rate`` (cache-hit pages over pages
    published + hit; 0.0 before any shared prefill). This is the
    timeline artifact's ``prefix`` section — all zeros on an engine
    without sharing or handoffs."""
    g = _generation_counters
    hits = g.get("gen_prefix_hits", 0.0)
    published = g.get("gen_prefix_published", 0.0)
    return {
        "prefix_hits": hits,
        "prefix_published": published,
        "hit_rate": (hits / (hits + published) if hits + published
                     else 0.0),
        "cow_copies": g.get("gen_cow_copies", 0.0),
        "prefix_degraded": g.get("gen_prefix_degraded", 0.0),
        "handoff_installs": g.get("gen_handoff_installs", 0.0),
        "handoff_failed": g.get("gen_handoff_failed", 0.0),
    }


def reset_generation_counters():
    _generation_counters.clear()


_MEM_MAX_KEYS = frozenset(("mem_predicted_peak_bytes",
                           "mem_measured_live_bytes"))


def update_memory_counters(**counters):
    """Accumulate static-memory-planner observability counters
    (paddle_tpu.analysis.memory; a few dict adds per PREFLIGHT/plan
    build — once per fresh compile, never per step). Keys in use:
    ``mem_preflights`` (executor pre-compile checks run),
    ``mem_plans`` (lint/accounting/elastic plan builds),
    ``mem_predicted_peak_bytes`` and ``mem_measured_live_bytes``
    (``jax.live_arrays`` evidence) — both kept as maxima, so the
    timeline's ``memory`` section reads as the run's high-water
    predicted-vs-actual pair."""
    for k, v in counters.items():
        if k in _MEM_MAX_KEYS:
            _memory_counters[k] = max(_memory_counters[k], float(v))
        else:
            _memory_counters[k] += float(v)


def memory_counters():
    """Snapshot {counter: value} of the static-memory-planner counters."""
    return dict(_memory_counters)


def reset_memory_counters():
    _memory_counters.clear()


_ROUTER_MAX_KEYS = frozenset(("router_peak_load", "router_replicas"))


def update_router_counters(**counters):
    """Accumulate multi-replica-router observability counters
    (paddle_tpu.serving.router/pool; a few dict adds per routed request
    or per supervision event, recorded in the ROUTER process — each
    replica keeps its own serving/generation counters). Keys in use:
    ``router_requests`` (proxied attempts), ``router_failovers``,
    ``router_no_replica`` (503s: no healthy replica),
    ``router_proxy_failed`` (503s: replicas were routable but both
    failover attempts died on transport), ``router_ejects``
    / ``router_readmits`` (health state transitions),
    ``router_reloads`` / ``router_reload_rollbacks`` (rolling hot
    reload outcomes), ``router_replica_restarts`` /
    ``router_replica_lost`` (pool supervision), ``router_gray_ejects``
    / ``router_gray_readmits`` (latency-skew ejections — replica
    answered /healthz 200 but the SkewDetector condemned its proxied
    latency EWMA), ``router_hedges`` / ``router_hedge_wins`` (hedged
    ``:predict`` attempts fired past the p99 deadline, and how many
    answered before the primary); ``router_peak_load``
    (largest per-replica load score observed by the poller) and
    ``router_replicas`` (configured pool size) are kept as maxima."""
    for k, v in counters.items():
        if k in _ROUTER_MAX_KEYS:
            _router_counters[k] = max(_router_counters[k], float(v))
        else:
            _router_counters[k] += float(v)


def router_counters():
    """Snapshot {counter: value} of the multi-replica-router counters."""
    return dict(_router_counters)


def reset_router_counters():
    _router_counters.clear()


_AUTOSCALE_MAX_KEYS = frozenset(("autoscale_replicas",
                                 "autoscale_pressure_max"))


def update_autoscale_counters(**counters):
    """Accumulate closed-loop-autoscaler observability counters
    (paddle_tpu.serving.autoscale; a few dict adds per control tick
    and per decision). Keys in use: ``autoscale_ticks`` (control-loop
    iterations), ``autoscale_ups`` / ``autoscale_downs`` (fleet
    resizes), ``autoscale_breaker_opens`` /
    ``autoscale_breaker_half_opens`` / ``autoscale_breaker_closes``
    (crash-loop circuit-breaker transitions),
    ``autoscale_breaker_refused`` (scale-ups the open breaker vetoed),
    ``autoscale_degraded`` (controller failures degraded to a fixed
    fleet); ``autoscale_replicas`` (largest fleet size reached) and
    ``autoscale_pressure_max`` (largest smoothed pressure observed)
    are kept as maxima."""
    for k, v in counters.items():
        if k in _AUTOSCALE_MAX_KEYS:
            _autoscale_counters[k] = max(_autoscale_counters[k],
                                         float(v))
        else:
            _autoscale_counters[k] += float(v)


def autoscale_counters():
    """Snapshot {counter: value} of the autoscaler counters."""
    return dict(_autoscale_counters)


def reset_autoscale_counters():
    _autoscale_counters.clear()


def update_grayfail_counters(**counters):
    """Accumulate gray-failure-detection observability counters
    (paddle_tpu.resilience.grayfail consumers — the elastic supervisor
    and the serving router; a few dict adds per detector verdict
    change or hedged request). Keys in use: ``gray_suspected`` (verdict
    escalations recorded at either tier), ``gray_mitigated_restarts``
    / ``gray_mitigated_resizes`` (the supervisor's budgeted
    mitigations of a condemned rank), ``gray_ejects`` /
    ``gray_readmits`` (the router's latency-only replica ejections and
    their probation returns), ``router_hedges`` (hedged :predict
    attempts fired past the p99 deadline) and ``router_hedge_wins``
    (hedges whose answer beat the primary)."""
    for k, v in counters.items():
        _grayfail_counters[k] += float(v)


def grayfail_counters():
    """Snapshot {counter: value} of the gray-failure counters."""
    return dict(_grayfail_counters)


def reset_grayfail_counters():
    _grayfail_counters.clear()


def record_op_event(op_type, name, t_start, t_end):
    """Per-op span from the eager interpreter path (on the jit path the
    per-op loop does not exist at run time — op granularity comes from the
    program analysis + xla_trace instead)."""
    _op_events.append({
        "name": "%s:%s" % (op_type, name), "cat": "op", "ph": "X",
        "ts": (t_start - _T0) * 1e6, "dur": (t_end - t_start) * 1e6,
        "pid": 0, "tid": 0,
        "args": {"op_type": op_type, "phase": _phase}})


_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def record_program_analysis(label, compiled, mesh_devices=1):
    """XLA's compiled cost analysis + a census of the collectives GSPMD
    inserted — the mesh 'barrier stat': every collective is a cross-device
    sync point (reference: platform/device_tracer.h timeline +
    profiler.proto role, in compiled-program form)."""
    entry = {"mesh_devices": int(mesh_devices)}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        entry["flops"] = float(ca.get("flops", 0.0))
        entry["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        text = compiled.as_text()
        coll = {}
        for kind in _COLLECTIVES:
            # "<kind>(" appears only at instruction call sites (operand
            # references are "%<kind>.N" — no open paren); async pairs
            # count once via -start
            n = text.count(" %s(" % kind) + text.count(" %s-start(" % kind)
            if n:
                coll[kind] = n
        entry["collectives"] = coll
        entry["barrier_points"] = sum(coll.values())
    except Exception:
        entry.setdefault("collectives", {})
        entry.setdefault("barrier_points", 0)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            entry["peak_device_memory_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0))
    except Exception:
        pass
    _program_analyses[label] = entry


def get_program_analysis(label):
    return _program_analyses.get(label)


def put_program_analysis(label, entry):
    if entry is not None:
        _program_analyses[label] = entry


def write_timeline(path):
    """Write the structured timeline artifact (JSON):

    - ``trace_events``: chrome-trace (catapult) spans — per-op eager spans
      and per-program run spans; loadable in chrome://tracing / Perfetto —
      the device_tracer.proto analog
      (reference: paddle/fluid/platform/device_tracer.h:30-60).
    - ``host_events``: aggregated wall-time table (profiler.h role).
    - ``programs``: per-compiled-program XLA cost analysis, collective
      census ('barrier stat' for mesh runs) and memory analysis.
    - ``pipeline``: async-execution-pipeline counters (feed-wait ms,
      dispatch depth, fetch syncs, compile-cache hits) — the overlap
      evidence for paddle_tpu.pipeline.
    - ``serving``: online-serving counters (requests, batches, padded
      rows, queue-wait ms, shed counts, max batch occupancy) — the
      coalescing evidence for paddle_tpu.serving.
    - ``comm``: gradient-communication counters (modelled wire bytes,
      bucket/dispatch counts, cumulative quant fallbacks) — the
      fusion/topology evidence for paddle_tpu.comm.
    - ``tune``: kernel-autotuning counters (winner-cache hits/misses/
      stock-XLA fallbacks at dispatch, autotune-loop activity) — the
      adoption evidence for paddle_tpu.tune.
    - ``elastic``: elasticity counters (resizes, lost ranks, requeued
      tasks, resume latency) — the survive-and-resize evidence for
      paddle_tpu.elastic.
    - ``generation``: autoregressive-serving counters (prefills, fused
      decode steps, generated tokens, running-batch/page-utilization
      maxima, sheds/preemptions) — the continuous-batching evidence for
      paddle_tpu.serving.generator.
    - ``router``: multi-replica-router counters (proxied requests,
      failovers, health ejects/readmits, rolling-reload outcomes,
      replica restarts, peak load score) — the fleet evidence for
      paddle_tpu.serving.router.
    - ``autoscale``: closed-loop-autoscaler counters (control ticks,
      scale-ups/downs, breaker transitions, degraded falls, max fleet
      size and max smoothed pressure) — the sizing evidence for
      paddle_tpu.serving.autoscale.
    - ``memory``: static-memory-planner counters (preflights/plans run,
      predicted peak vs ``jax.live_arrays`` measured high-water — the
      predicted-vs-actual evidence for paddle_tpu.analysis.memory).
    - ``trainer``: trainer-loop failure-policy counters (guardrail
      batch skips and rewinds, watchdog step_hung firings, elastic
      lease commits, truncated preemptions — the survival evidence
      for the elastic Trainer worker).
    """
    import json
    rows = []
    for label, times in _records.items():
        n = len(times)
        total = sum(times)
        rows.append({"name": label, "calls": n, "total_ms": total * 1e3,
                     "avg_ms": total / n * 1e3,
                     "min_ms": min(times) * 1e3,
                     "max_ms": max(times) * 1e3})
    artifact = {
        "schema": "paddle_tpu.timeline.v1",
        "trace_events": list(_op_events),
        "host_events": rows,
        "programs": dict(_program_analyses),
        "pipeline": dict(_pipeline_counters),
        "serving": dict(_serving_counters),
        "comm": dict(_comm_counters),
        "tune": dict(_tune_counters),
        "elastic": dict(_elastic_counters),
        "generation": dict(_generation_counters),
        "speculation": speculation_counters(),
        "prefix": prefix_counters(),
        "router": dict(_router_counters),
        "autoscale": dict(_autoscale_counters),
        "memory": dict(_memory_counters),
        "trainer": dict(_trainer_counters),
        "grayfail": dict(_grayfail_counters),
    }
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    return artifact


def stop_profiler(sorted_key=None, profile_path=None):
    """Print the aggregated per-program table
    (reference: platform/profiler.h:138-151 PrintProfiler)."""
    global _enabled
    _enabled = False
    rows = []
    for label, times in _records.items():
        n = len(times)
        total = sum(times)
        rows.append((label, n, total, total / n, min(times), max(times)))
    key = {None: lambda r: 0, "default": lambda r: 0,
           "calls": lambda r: -r[1], "total": lambda r: -r[2],
           "ave": lambda r: -r[3], "min": lambda r: -r[4],
           "max": lambda r: -r[5]}.get(sorted_key, lambda r: 0)
    rows.sort(key=key)
    lines = ["%-40s %8s %12s %12s %12s %12s" %
             ("Event", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)", "Max(ms)")]
    for label, n, total, avg, mn, mx in rows:
        lines.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" %
                     (label, n, total * 1e3, avg * 1e3, mn * 1e3, mx * 1e3))
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report + "\n")
    print(report)
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             timeline_path=None):
    """reference: profiler.py:125 profiler context manager. Pass
    ``timeline_path`` to also write the structured JSON timeline artifact
    (see write_timeline)."""
    start_profiler(state)
    reset_profiler()
    try:
        yield
    finally:
        try:
            if timeline_path:
                write_timeline(timeline_path)
        finally:
            stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Device-timeline capture. The reference wraps nvprof
    (profiler.py:20-60); the TPU analog is an xplane trace directory
    loadable in TensorBoard/XProf."""
    if output_file:
        with xla_trace(output_file):
            yield
    else:
        yield


@contextlib.contextmanager
def xla_trace(logdir):
    """jax.profiler trace — kernel timeline, HBM usage, per-fusion costs
    (device_tracer equivalent; reference: platform/device_tracer.h:30-60)."""
    import jax
    jax.profiler.start_trace(str(logdir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def record_event(name):
    """Host-side RAII timer (reference: platform/profiler.h RecordEvent)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_run(name, time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Hierarchical stats: the REGISTER_TIMER role (reference: paddle/utils/Stat.h
# — per-name accumulated timers printed as a tree every log period, plus
# BarrierStat for straggler analysis across trainers). Here: nested `timer`
# scopes accumulate (count/total/max) per dotted path; `print_stats` renders
# the tree; `BarrierStat.observe` records per-member arrival times of a
# collective/barrier and reports the straggler gap.

import threading as _threading

_stat_state = _threading.local()


class _StatNode(object):
    __slots__ = ("count", "total", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def add(self, dt):
        self.count += 1
        self.total += dt
        self.max = max(self.max, dt)


_stats = {}
_stats_lock = _threading.Lock()


@contextlib.contextmanager
def timer(name):
    """Accumulating hierarchical timer: nesting builds dotted paths.

    >>> with profiler.timer("forward"):
    ...     with profiler.timer("conv"):   # recorded as "forward.conv"
    ...         ...
    """
    stack = getattr(_stat_state, "stack", None)
    if stack is None:
        stack = _stat_state.stack = []
    stack.append(name)
    path = ".".join(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        with _stats_lock:
            _stats.setdefault(path, _StatNode()).add(dt)


def stat_summary():
    """{path: (count, total_s, avg_s, max_s)} snapshot."""
    with _stats_lock:
        return {p: (n.count, n.total, n.total / n.count, n.max)
                for p, n in _stats.items() if n.count}


def print_stats(file=None):
    """Render the timer tree (REGISTER_TIMER print analog)."""
    import sys as _sys
    out = file or _sys.stdout
    snap = stat_summary()
    if not snap:
        print("(no stats recorded)", file=out)
        return
    print("%-40s %8s %12s %12s %12s" %
          ("timer", "count", "total_ms", "avg_ms", "max_ms"), file=out)
    for path in sorted(snap):
        cnt, tot, avg, mx = snap[path]
        depth = path.count(".")
        label = "  " * depth + path.rsplit(".", 1)[-1]
        print("%-40s %8d %12.3f %12.3f %12.3f" %
              (label, cnt, 1e3 * tot, 1e3 * avg, 1e3 * mx), file=out)


def reset_stats():
    with _stats_lock:
        _stats.clear()


class BarrierStat(object):
    """Straggler analysis for an N-member barrier (reference:
    paddle/pserver/ParameterServer2 BarrierStat / utils/Stat.h): feed each
    member's arrival timestamp per round; report the slowest-minus-fastest
    gap and which member lags most often."""

    def __init__(self, n_members, name="barrier"):
        self.n = n_members
        self.name = name
        self._round = {}
        self._gaps = []
        self._slowest = {}  # member id (any hashable) -> lag-round count
        self._lock = _threading.Lock()

    def observe(self, member, t=None):
        t = time.perf_counter() if t is None else t
        with self._lock:
            self._round[member] = t
            if len(self._round) == self.n:
                ts = self._round
                fastest = min(ts, key=ts.get)
                slowest = max(ts, key=ts.get)
                self._gaps.append(ts[slowest] - ts[fastest])
                self._slowest[slowest] = self._slowest.get(slowest, 0) + 1
                self._round = {}

    def summary(self):
        with self._lock:
            if not self._gaps:
                return {"rounds": 0}
            worst = max(self._slowest, key=self._slowest.get)
            return {
                "rounds": len(self._gaps),
                "mean_gap_s": sum(self._gaps) / len(self._gaps),
                "max_gap_s": max(self._gaps),
                "worst_member": worst,
                "worst_member_lag_rounds": self._slowest[worst],
            }
