"""Profiling: per-run host timers + XLA/xplane trace capture.

reference: python/paddle/fluid/profiler.py:20-125 (profiler / cuda_profiler
context managers over the C++ RecordEvent profiler,
paddle/fluid/platform/profiler.h:60-151) and platform/device_tracer.h (CUPTI
timeline). On TPU the per-op host loop doesn't exist — one jitted program is
one device launch — so the host profiler records per-run wall/compile times
per program, and the device timeline comes from jax.profiler's xplane trace
(TensorBoard-compatible), which is the CUPTI-tracer equivalent.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

__all__ = ["start_profiler", "stop_profiler", "reset_profiler", "profiler",
           "cuda_profiler", "xla_trace", "profiler_enabled", "record_run",
           "record_op_event", "record_program_analysis", "write_timeline"]

_enabled = False
_records = defaultdict(list)  # label -> [seconds]
_op_events = []               # chrome-trace X events (eager per-op spans)
_program_analyses = {}        # label -> {flops, bytes, collectives, ...}
_T0 = time.perf_counter()


def profiler_enabled():
    return _enabled


_phase = "eager"


def set_phase(phase):
    """'eager' = per-op spans are real run time; 'trace' = spans measure
    trace/lowering cost (the jit path runs as one fused program)."""
    global _phase
    _phase = phase


def record_run(label, seconds):
    """Called by Executor.run while profiling is on."""
    if _enabled:
        _records[label].append(seconds)
        t_end = time.perf_counter()
        _op_events.append({
            "name": label, "cat": "program", "ph": "X",
            "ts": (t_end - seconds - _T0) * 1e6, "dur": seconds * 1e6,
            "pid": 0, "tid": 1, "args": {}})


def start_profiler(state="All"):
    """reference: profiler.py start_profiler (state CPU/GPU/All — moot on
    TPU: the device timeline needs xla_trace instead)."""
    global _enabled
    _enabled = True


def reset_profiler():
    _records.clear()
    del _op_events[:]
    _program_analyses.clear()


def record_op_event(op_type, name, t_start, t_end):
    """Per-op span from the eager interpreter path (on the jit path the
    per-op loop does not exist at run time — op granularity comes from the
    program analysis + xla_trace instead)."""
    _op_events.append({
        "name": "%s:%s" % (op_type, name), "cat": "op", "ph": "X",
        "ts": (t_start - _T0) * 1e6, "dur": (t_end - t_start) * 1e6,
        "pid": 0, "tid": 0,
        "args": {"op_type": op_type, "phase": _phase}})


_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def record_program_analysis(label, compiled, mesh_devices=1):
    """XLA's compiled cost analysis + a census of the collectives GSPMD
    inserted — the mesh 'barrier stat': every collective is a cross-device
    sync point (reference: platform/device_tracer.h timeline +
    profiler.proto role, in compiled-program form)."""
    entry = {"mesh_devices": int(mesh_devices)}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        entry["flops"] = float(ca.get("flops", 0.0))
        entry["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        text = compiled.as_text()
        coll = {}
        for kind in _COLLECTIVES:
            # "<kind>(" appears only at instruction call sites (operand
            # references are "%<kind>.N" — no open paren); async pairs
            # count once via -start
            n = text.count(" %s(" % kind) + text.count(" %s-start(" % kind)
            if n:
                coll[kind] = n
        entry["collectives"] = coll
        entry["barrier_points"] = sum(coll.values())
    except Exception:
        entry.setdefault("collectives", {})
        entry.setdefault("barrier_points", 0)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            entry["peak_device_memory_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0))
    except Exception:
        pass
    _program_analyses[label] = entry


def get_program_analysis(label):
    return _program_analyses.get(label)


def put_program_analysis(label, entry):
    if entry is not None:
        _program_analyses[label] = entry


def write_timeline(path):
    """Write the structured timeline artifact (JSON):

    - ``trace_events``: chrome-trace (catapult) spans — per-op eager spans
      and per-program run spans; loadable in chrome://tracing / Perfetto —
      the device_tracer.proto analog
      (reference: paddle/fluid/platform/device_tracer.h:30-60).
    - ``host_events``: aggregated wall-time table (profiler.h role).
    - ``programs``: per-compiled-program XLA cost analysis, collective
      census ('barrier stat' for mesh runs) and memory analysis.
    """
    import json
    rows = []
    for label, times in _records.items():
        n = len(times)
        total = sum(times)
        rows.append({"name": label, "calls": n, "total_ms": total * 1e3,
                     "avg_ms": total / n * 1e3,
                     "min_ms": min(times) * 1e3,
                     "max_ms": max(times) * 1e3})
    artifact = {
        "schema": "paddle_tpu.timeline.v1",
        "trace_events": list(_op_events),
        "host_events": rows,
        "programs": dict(_program_analyses),
    }
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    return artifact


def stop_profiler(sorted_key=None, profile_path=None):
    """Print the aggregated per-program table
    (reference: platform/profiler.h:138-151 PrintProfiler)."""
    global _enabled
    _enabled = False
    rows = []
    for label, times in _records.items():
        n = len(times)
        total = sum(times)
        rows.append((label, n, total, total / n, min(times), max(times)))
    key = {None: lambda r: 0, "default": lambda r: 0,
           "calls": lambda r: -r[1], "total": lambda r: -r[2],
           "ave": lambda r: -r[3], "min": lambda r: -r[4],
           "max": lambda r: -r[5]}.get(sorted_key, lambda r: 0)
    rows.sort(key=key)
    lines = ["%-40s %8s %12s %12s %12s %12s" %
             ("Event", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)", "Max(ms)")]
    for label, n, total, avg, mn, mx in rows:
        lines.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" %
                     (label, n, total * 1e3, avg * 1e3, mn * 1e3, mx * 1e3))
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report + "\n")
    print(report)
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             timeline_path=None):
    """reference: profiler.py:125 profiler context manager. Pass
    ``timeline_path`` to also write the structured JSON timeline artifact
    (see write_timeline)."""
    start_profiler(state)
    reset_profiler()
    try:
        yield
    finally:
        try:
            if timeline_path:
                write_timeline(timeline_path)
        finally:
            stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Device-timeline capture. The reference wraps nvprof
    (profiler.py:20-60); the TPU analog is an xplane trace directory
    loadable in TensorBoard/XProf."""
    if output_file:
        with xla_trace(output_file):
            yield
    else:
        yield


@contextlib.contextmanager
def xla_trace(logdir):
    """jax.profiler trace — kernel timeline, HBM usage, per-fusion costs
    (device_tracer equivalent; reference: platform/device_tracer.h:30-60)."""
    import jax
    jax.profiler.start_trace(str(logdir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def record_event(name):
    """Host-side RAII timer (reference: platform/profiler.h RecordEvent)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_run(name, time.perf_counter() - t0)
