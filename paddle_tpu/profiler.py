"""Profiling: per-run host timers + XLA/xplane trace capture.

reference: python/paddle/fluid/profiler.py:20-125 (profiler / cuda_profiler
context managers over the C++ RecordEvent profiler,
paddle/fluid/platform/profiler.h:60-151) and platform/device_tracer.h (CUPTI
timeline). On TPU the per-op host loop doesn't exist — one jitted program is
one device launch — so the host profiler records per-run wall/compile times
per program, and the device timeline comes from jax.profiler's xplane trace
(TensorBoard-compatible), which is the CUPTI-tracer equivalent.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

__all__ = ["start_profiler", "stop_profiler", "reset_profiler", "profiler",
           "cuda_profiler", "xla_trace", "profiler_enabled", "record_run"]

_enabled = False
_records = defaultdict(list)  # label -> [seconds]


def profiler_enabled():
    return _enabled


def record_run(label, seconds):
    """Called by Executor.run while profiling is on."""
    if _enabled:
        _records[label].append(seconds)


def start_profiler(state="All"):
    """reference: profiler.py start_profiler (state CPU/GPU/All — moot on
    TPU: the device timeline needs xla_trace instead)."""
    global _enabled
    _enabled = True


def reset_profiler():
    _records.clear()


def stop_profiler(sorted_key=None, profile_path=None):
    """Print the aggregated per-program table
    (reference: platform/profiler.h:138-151 PrintProfiler)."""
    global _enabled
    _enabled = False
    rows = []
    for label, times in _records.items():
        n = len(times)
        total = sum(times)
        rows.append((label, n, total, total / n, min(times), max(times)))
    key = {None: lambda r: 0, "default": lambda r: 0,
           "calls": lambda r: -r[1], "total": lambda r: -r[2],
           "ave": lambda r: -r[3], "min": lambda r: -r[4],
           "max": lambda r: -r[5]}.get(sorted_key, lambda r: 0)
    rows.sort(key=key)
    lines = ["%-40s %8s %12s %12s %12s %12s" %
             ("Event", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)", "Max(ms)")]
    for label, n, total, avg, mn, mx in rows:
        lines.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" %
                     (label, n, total * 1e3, avg * 1e3, mn * 1e3, mx * 1e3))
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report + "\n")
    print(report)
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """reference: profiler.py:125 profiler context manager."""
    start_profiler(state)
    reset_profiler()
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Device-timeline capture. The reference wraps nvprof
    (profiler.py:20-60); the TPU analog is an xplane trace directory
    loadable in TensorBoard/XProf."""
    if output_file:
        with xla_trace(output_file):
            yield
    else:
        yield


@contextlib.contextmanager
def xla_trace(logdir):
    """jax.profiler trace — kernel timeline, HBM usage, per-fusion costs
    (device_tracer equivalent; reference: platform/device_tracer.h:30-60)."""
    import jax
    jax.profiler.start_trace(str(logdir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def record_event(name):
    """Host-side RAII timer (reference: platform/profiler.h RecordEvent)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_run(name, time.perf_counter() - t0)
