"""DataFeeder: convert reader minibatches into the Executor feed dict.

reference: python/paddle/fluid/data_feeder.py:118 (DataFeeder /
DataToLoDTensorConverter) — rows of python/numpy values become dense arrays,
lod_level>0 fields become LoDTensors with offsets built from nested lists.
"""
from __future__ import annotations

import numpy as np

from .core.ir import Variable
from .core.lod import LoDTensor, lengths_to_offsets
from .core.types import convert_dtype


class DataToLoDTensorConverter(object):
    def __init__(self, lod_level, shape, dtype):
        self.lod_level = lod_level
        self.shape = tuple(s for s in shape if s != -1) if shape else ()
        self.dtype = dtype
        self.data = []
        self.lod = [[] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            if self.shape and arr.ndim == 1 and len(self.shape) > 0:
                try:
                    arr = arr.reshape((-1,) + self.shape)
                except ValueError:
                    pass
            return arr
        flat = np.array(self.data, dtype=self.dtype)
        if self.shape:
            try:
                flat = flat.reshape((-1,) + self.shape)
            except ValueError:
                pass
        if flat.ndim == 1:
            flat = flat.reshape(-1, 1)
        t = LoDTensor(flat, [lengths_to_offsets(l) for l in self.lod])
        return t


class DataFeeder(object):
    """reference: python/paddle/fluid/data_feeder.py DataFeeder."""

    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        for each_var in feed_list:
            if isinstance(each_var, str):
                from .core.ir import default_main_program
                each_var = (program or default_main_program()) \
                    .global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list entries must be Variables/names")
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(convert_dtype(each_var.dtype))
        self.place = place
        # per-field converter specs, resolved once: feed() builds fresh
        # converters from these each call, so it carries no mutable state
        # between calls — safe to run on the async pipeline's feed thread
        # concurrently with Executor.run on the main thread
        self._converter_specs = list(zip(self.feed_lod_level,
                                         self.feed_shapes,
                                         self.feed_dtypes))

    def feed(self, iterable):
        """Minibatch (iterable of per-sample field tuples) -> feed dict.
        Stateless per call (thread-safe; see _converter_specs)."""
        converters = [
            DataToLoDTensorConverter(lod_level=lod, shape=shape or (),
                                     dtype=dtype)
            for lod, shape, dtype in self._converter_specs]
        for each_sample in iterable:
            if len(each_sample) != len(converters):
                raise ValueError(
                    "sample has %d fields, feed_list expects %d"
                    % (len(each_sample), len(converters)))
            for value, conv in zip(each_sample, converters):
                conv.feed(value)
        return {name: conv.done()
                for name, conv in zip(self.feed_names, converters)}
