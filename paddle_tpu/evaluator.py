"""Stateful evaluators accumulating metrics across batches.

reference: python/paddle/fluid/evaluator.py:268 (Evaluator base, Accuracy,
ChunkEvaluator, EditDistance). States are persistable vars in the main
program; per-batch ops fold the batch statistic into the state inside the
same jitted step, ``reset`` zeroes them via a tiny side program, ``eval``
reads them back from the scope.
"""
from __future__ import annotations

import numpy as np

from . import layers
from .core import ir, unique_name
from .core.executor import fetch_var
from .core.scope import global_scope
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper

__all__ = ["Evaluator", "Accuracy", "ChunkEvaluator", "EditDistance"]


class Evaluator(object):
    """reference: evaluator.py Evaluator — subclasses create states in
    __init__ and append update ops to the main program."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        """Zero all states (reference: evaluator.py Evaluator.reset)."""
        if reset_program is None:
            reset_program = ir.Program()
        with ir.program_guard(main_program=reset_program):
            for var in self.states:
                blk = reset_program.global_block()
                zv = blk.create_var(name=var.name, shape=var.shape,
                                    dtype=var.dtype, persistable=True)
                layers.fill_constant(shape=var.shape, dtype=var.dtype,
                                     value=0.0, out=zv)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.create_global_variable(
            name=unique_name.generate(self.helper.name + "_" + suffix),
            shape=shape, dtype=dtype, persistable=True)
        self.helper.set_variable_initializer(state, ConstantInitializer(0.0))
        self.states.append(state)
        return state

    def _accumulate(self, state, batch_value):
        """state += batch_value, written back onto the state var."""
        self.helper.append_op(type="elementwise_add",
                              inputs={"X": [state], "Y": [batch_value]},
                              outputs={"Out": [state]})

    def _state_value(self, state):
        v = fetch_var(state.name, global_scope())
        return np.asarray(v)


class Accuracy(Evaluator):
    """Streaming top-k accuracy (reference: evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super(Accuracy, self).__init__("accuracy", **kwargs)
        self.total = self._create_state("total", "int32", (1,))
        self.correct = self._create_state("correct", "int32", (1,))
        correct = self.helper.create_variable_for_type_inference("int32")
        total = self.helper.create_variable_for_type_inference("int32")
        acc = layers.accuracy(input, label, k=k, correct=correct, total=total)
        self._accumulate(self.total, total)
        self._accumulate(self.correct, correct)
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        total = float(self._state_value(self.total)[0])
        correct = float(self._state_value(self.correct)[0])
        return np.array(correct / max(total, 1.0), dtype="float32")


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (NER-style; reference: evaluator.py
    ChunkEvaluator over operators/chunk_eval_op)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, **kwargs):
        super(ChunkEvaluator, self).__init__("chunk_eval", **kwargs)
        self.num_infer_chunks = self._create_state("num_infer", "int64", (1,))
        self.num_label_chunks = self._create_state("num_label", "int64", (1,))
        self.num_correct_chunks = self._create_state("num_correct", "int64",
                                                     (1,))
        (precision, recall, f1, num_infer, num_label,
         num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self._accumulate(self.num_infer_chunks, num_infer)
        self._accumulate(self.num_label_chunks, num_label)
        self._accumulate(self.num_correct_chunks, num_correct)
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None):
        num_infer = float(self._state_value(self.num_infer_chunks)[0])
        num_label = float(self._state_value(self.num_label_chunks)[0])
        num_correct = float(self._state_value(self.num_correct_chunks)[0])
        precision = num_correct / num_infer if num_infer else 0.0
        recall = num_correct / num_label if num_label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if num_correct else 0.0)
        return (np.float32(precision), np.float32(recall), np.float32(f1))


class EditDistance(Evaluator):
    """Streaming average edit distance + sequence error rate
    (reference: evaluator.py EditDistance)."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super(EditDistance, self).__init__("edit_distance", **kwargs)
        self.total_distance = self._create_state("total_distance", "float32",
                                                 (1,))
        self.seq_num = self._create_state("seq_num", "int64", (1,))
        self.instance_error = self._create_state("instance_error", "int64",
                                                 (1,))
        distances, seq_num = layers.edit_distance(input, label,
                                                  ignored_tokens=ignored_tokens)
        zero = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        errors = layers.cast(
            layers.reduce_sum(
                layers.cast(distances > zero, "float32")), "int64")
        errors = layers.reshape(errors, shape=[1])
        total = layers.reduce_sum(distances)
        total = layers.reshape(total, shape=[1])
        self._accumulate(self.total_distance, total)
        self._accumulate(self.seq_num, seq_num)
        self._accumulate(self.instance_error, errors)
        self.metrics.append(distances)

    def eval(self, executor, eval_program=None):
        total = float(self._state_value(self.total_distance)[0])
        seq_num = float(self._state_value(self.seq_num)[0])
        err = float(self._state_value(self.instance_error)[0])
        avg = total / max(seq_num, 1.0)
        rate = err / max(seq_num, 1.0)
        return np.float32(avg), np.float32(rate)
