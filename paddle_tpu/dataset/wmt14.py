"""WMT-14 fr->en. reference: python/paddle/v2/dataset/wmt14.py — rows of
(src_ids, trg_ids_with_<s>, trg_ids_next_with_<e>); ids 0/1/2 are
<s>/<e>/<unk>.

When the real ``wmt14.tgz`` (the reference's preprocessed
wmt_shrinked_data archive) is present under ``<data_home>/wmt14/``, it
is parsed the reference's way: ``src.dict``/``trg.dict`` members
truncated to dict_size (line number = id, first three lines are
<s>/<e>/<unk>), sentence pairs tab-separated in the ``train/train`` and
``test/test`` members, source wrapped in <s>...<e>, pairs longer than
80 tokens dropped. The synthetic fallback keeps its (documented)
unwrapped source convention."""
from __future__ import annotations

import tarfile

from . import common

__all__ = ["train", "test", "START", "END", "UNK"]

START, END, UNK = 0, 1, 2
TRAIN_SIZE = 512
TEST_SIZE = 64

_MEMBERS = {"train": "train/train", "test": "test/test"}


def _archive():
    return common.cached_file("wmt14", "wmt14.tgz")


def _read_dicts(tar_path, dict_size):
    def to_dict(fd, size):
        d = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            d[line.decode("utf-8", "replace").strip()] = i
        return d

    with tarfile.open(tar_path) as f:
        src = [m.name for m in f if m.name.endswith("src.dict")]
        trg = [m.name for m in f if m.name.endswith("trg.dict")]
        return (to_dict(f.extractfile(src[0]), dict_size),
                to_dict(f.extractfile(trg[0]), dict_size))


def _real_reader(tar_path, split, dict_size):
    def reader():
        src_dict, trg_dict = _read_dicts(tar_path, dict_size)
        end_id, start_id = trg_dict["<e>"], trg_dict["<s>"]
        with tarfile.open(tar_path) as f:
            names = [m.name for m in f
                     if m.name.endswith(_MEMBERS[split])]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode("utf-8", "replace") \
                        .strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK) for w in
                               ["<s>"] + parts[0].split() + ["<e>"]]
                    trg_ids = [trg_dict.get(w, UNK)
                               for w in parts[1].split()]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    yield (src_ids, [start_id] + trg_ids,
                           trg_ids + [end_id])

    return reader


def _reader(n, split, dict_size):
    tar = _archive()
    if tar:
        return _real_reader(tar, split, dict_size)

    def reader():
        rng = common.seeded_rng("wmt14-" + split)
        for _ in range(n):
            slen = int(rng.randint(3, 15))
            src = [int(w) for w in rng.randint(3, dict_size, slen)]
            # target: deterministic "translation" (reverse + shift) so
            # seq2seq models can learn the mapping
            trg = [(w + 7) % (dict_size - 3) + 3 for w in reversed(src)]
            yield src, [START] + trg, trg + [END]

    return reader


def train(dict_size):
    return _reader(TRAIN_SIZE, "train", dict_size)


def test(dict_size):
    return _reader(TEST_SIZE, "test", dict_size)
