"""WMT-14 fr->en. reference: python/paddle/v2/dataset/wmt14.py — rows of
(src_ids, trg_ids_with_<s>, trg_ids_next_with_<e>); ids 0/1/2 are
<s>/<e>/<unk>."""
from __future__ import annotations

from . import common

__all__ = ["train", "test", "START", "END", "UNK"]

START, END, UNK = 0, 1, 2
TRAIN_SIZE = 512
TEST_SIZE = 64


def _reader(n, split, dict_size):
    def reader():
        rng = common.seeded_rng("wmt14-" + split)
        for _ in range(n):
            slen = int(rng.randint(3, 15))
            src = [int(w) for w in rng.randint(3, dict_size, slen)]
            # target: deterministic "translation" (reverse + shift) so
            # seq2seq models can learn the mapping
            trg = [(w + 7) % (dict_size - 3) + 3 for w in reversed(src)]
            yield src, [START] + trg, trg + [END]

    return reader


def train(dict_size):
    return _reader(TRAIN_SIZE, "train", dict_size)


def test(dict_size):
    return _reader(TEST_SIZE, "test", dict_size)
