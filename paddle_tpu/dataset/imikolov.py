"""PTB language model (imikolov). reference:
python/paddle/v2/dataset/imikolov.py — build_dict() then train(word_idx, n)
yields n-gram tuples of word ids (the word2vec book test feeds n=5)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test"]

VOCAB = 2074
TRAIN_SENT = 512
TEST_SENT = 128


def build_dict(min_word_freq=50):
    d = {"<w%d>" % i: i for i in range(VOCAB - 2)}
    d["<unk>"] = VOCAB - 2
    d["<e>"] = VOCAB - 1
    return d


def _sentences(split, n_sent):
    rng = common.seeded_rng("imikolov-" + split)
    # markov-ish chains so n-gram models have signal
    trans = common.seeded_rng("imikolov-trans").randint(0, VOCAB, VOCAB)
    for _ in range(n_sent):
        length = int(rng.randint(5, 25))
        w = int(rng.randint(0, VOCAB))
        sent = [w]
        for _ in range(length - 1):
            w = int((trans[w] + rng.randint(0, 7)) % VOCAB)
            sent.append(w)
        yield sent


def _ngram_reader(split, n_sent, word_idx, n):
    def reader():
        for sent in _sentences(split, n_sent):
            if len(sent) >= n:
                sent = [min(w, len(word_idx) - 1) for w in sent]
                for i in range(n, len(sent) + 1):
                    yield tuple(sent[i - n:i])

    return reader


def train(word_idx, n):
    return _ngram_reader("train", TRAIN_SENT, word_idx, n)


def test(word_idx, n):
    return _ngram_reader("test", TEST_SENT, word_idx, n)
