"""PTB language model (imikolov). reference:
python/paddle/v2/dataset/imikolov.py — build_dict() then train(word_idx, n)
yields n-gram tuples of word ids (the word2vec book test feeds n=5).

When the real ``simple-examples.tgz`` (the archive the reference's
download() caches) is present under ``<data_home>/imikolov/``, its
``data/ptb.{train,valid}.txt`` members are parsed with the reference's
exact pipeline: frequency dict over train+valid with ``<e>`` appended
per line, min_word_freq filter, (-freq, word) sort order, ``<unk>``
appended last; readers wrap each line as ``<s> ... <e>`` and emit
n-grams with unknown words mapped to ``<unk>``. Otherwise a
deterministic synthetic corpus is generated."""
from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "train", "test"]

VOCAB = 2074
TRAIN_SENT = 512
TEST_SENT = 128


_MEMBERS = {"train": "data/ptb.train.txt", "test": "data/ptb.valid.txt"}


def _archive():
    return common.cached_file("imikolov", "simple-examples.tgz")


def _read_lines(tar_path, member):
    with tarfile.open(tar_path) as tf:
        for m in tf.getmembers():
            if m.name.endswith(member):
                f = tf.extractfile(m)
                return [l.decode("utf-8", "replace") for l in f.readlines()]
    raise ValueError("%s: no member ending in %r" % (tar_path, member))


def _word_count(lines, freq):
    for l in lines:
        for w in l.strip().split():
            freq[w] = freq.get(w, 0) + 1
        freq["<e>"] = freq.get("<e>", 0) + 1
    return freq


def build_dict(min_word_freq=50):
    tar = _archive()
    if tar:
        freq = _word_count(_read_lines(tar, _MEMBERS["train"]), {})
        freq = _word_count(_read_lines(tar, _MEMBERS["test"]), freq)
        freq.pop("<unk>", None)
        kept = [(w, c) for w, c in freq.items() if c > min_word_freq]
        kept.sort(key=lambda t: (-t[1], t[0]))
        d = {w: i for i, (w, _) in enumerate(kept)}
        d["<unk>"] = len(d)
        return d
    d = {"<w%d>" % i: i for i in range(VOCAB - 2)}
    d["<unk>"] = VOCAB - 2
    d["<e>"] = VOCAB - 1
    return d


def _sentences(split, n_sent):
    rng = common.seeded_rng("imikolov-" + split)
    # markov-ish chains so n-gram models have signal
    trans = common.seeded_rng("imikolov-trans").randint(0, VOCAB, VOCAB)
    for _ in range(n_sent):
        length = int(rng.randint(5, 25))
        w = int(rng.randint(0, VOCAB))
        sent = [w]
        for _ in range(length - 1):
            w = int((trans[w] + rng.randint(0, 7)) % VOCAB)
            sent.append(w)
        yield sent


def _ngram_reader(split, n_sent, word_idx, n):
    tar = _archive()
    if tar:
        def reader():
            unk = word_idx["<unk>"]
            for l in _read_lines(tar, _MEMBERS[split]):
                toks = ["<s>"] + l.strip().split() + ["<e>"]
                ids = [word_idx.get(w, unk) for w in toks]
                if len(ids) >= n:
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])

        return reader

    def reader():
        for sent in _sentences(split, n_sent):
            if len(sent) >= n:
                sent = [min(w, len(word_idx) - 1) for w in sent]
                for i in range(n, len(sent) + 1):
                    yield tuple(sent[i - n:i])

    return reader


def train(word_idx, n):
    return _ngram_reader("train", TRAIN_SENT, word_idx, n)


def test(word_idx, n):
    return _ngram_reader("test", TEST_SENT, word_idx, n)
