"""PASCAL VOC2012 segmentation. reference:
python/paddle/v2/dataset/voc2012.py — rows of (image [3,H,W], seg label
[H,W] int in [0,21))."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

H = W = 64   # synthetic resolution (real images vary)
TRAIN_SIZE = 64
TEST_SIZE = 16


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("voc2012-" + split)
        for _ in range(n):
            img = rng.uniform(0, 1, (3, H, W)).astype(np.float32)
            label = np.zeros((H, W), np.int32)
            cls = int(rng.randint(1, 21))
            x0, y0 = rng.randint(0, H // 2), rng.randint(0, W // 2)
            label[x0:x0 + H // 2, y0:y0 + W // 2] = cls
            img[0, x0:x0 + H // 2, y0:y0 + W // 2] += 0.5
            yield img, label

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")


def val():
    return _reader(TEST_SIZE, "val")
