"""PASCAL VOC2012 segmentation. reference:
python/paddle/v2/dataset/voc2012.py — rows of (image [3,H,W], seg label
[H,W] int in [0,21)).

When the real ``VOCtrainval_11-May-2012.tar`` is present under
``<data_home>/voc2012/``, it is parsed exactly like the reference:
ids from ``ImageSets/Segmentation/{trainval,train,val}.txt`` with the
reference's split mapping (train() -> trainval, test() -> train,
val() -> val — voc2012.py:67-81), jpg decoded to an HWC uint8 array and
the palette png to an HW uint8 array of class indices (border pixels
keep the VOC value 255), both yielded raw like the reference. The
synthetic fallback below keeps its own (documented) CHW-float contract
for shape-stable tests."""
from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

H = W = 64   # synthetic resolution (real images vary)
TRAIN_SIZE = 64
TEST_SIZE = 16

_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/%s.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/%s.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/%s.png"
# reference split mapping (voc2012.py:67-81): its test() reads 'train'
_SUBSETS = {"train": "trainval", "test": "train", "val": "val"}


def _archive():
    return common.cached_file("voc2012", "VOCtrainval_11-May-2012.tar")


def _real_reader(tar_path, split):
    def reader():
        import io

        from PIL import Image
        with tarfile.open(tar_path) as tf:
            members = {m.name: m for m in tf.getmembers()}
            ids = tf.extractfile(
                members[_SET_FILE % _SUBSETS[split]]).read() \
                .decode().split()
            for line in ids:
                img = Image.open(io.BytesIO(tf.extractfile(
                    members[_DATA_FILE % line]).read()))
                lbl = Image.open(io.BytesIO(tf.extractfile(
                    members[_LABEL_FILE % line]).read()))
                yield np.array(img), np.array(lbl)

    return reader


def _reader(n, split):
    tar = _archive()
    if tar:
        return _real_reader(tar, split)

    def reader():
        rng = common.seeded_rng("voc2012-" + split)
        for _ in range(n):
            img = rng.uniform(0, 1, (3, H, W)).astype(np.float32)
            label = np.zeros((H, W), np.int32)
            cls = int(rng.randint(1, 21))
            x0, y0 = rng.randint(0, H // 2), rng.randint(0, W // 2)
            label[x0:x0 + H // 2, y0:y0 + W // 2] = cls
            img[0, x0:x0 + H // 2, y0:y0 + W // 2] += 0.5
            yield img, label

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")


def val():
    return _reader(TEST_SIZE, "val")
