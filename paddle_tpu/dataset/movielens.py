"""MovieLens-1M. reference: python/paddle/v2/dataset/movielens.py — rows of
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
score); plus max_*_id helpers the recommender book test uses."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "movie_categories", "user_info",
           "movie_info", "age_table"]

_N_USERS = 600
_N_MOVIES = 400
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 512
age_table = [1, 18, 25, 35, 45, 50, 56]

TRAIN_SIZE = 2048
TEST_SIZE = 256


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {"<c%d>" % i: i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {"<t%d>" % i: i for i in range(_TITLE_VOCAB)}


def user_info():
    rng = common.seeded_rng("ml-users")
    return {i: (i, int(rng.randint(0, 2)), int(rng.randint(0, len(age_table))),
                int(rng.randint(0, _N_JOBS)))
            for i in range(1, _N_USERS + 1)}


def movie_info():
    rng = common.seeded_rng("ml-movies")
    return {i: (i,
                sorted(set(int(c) for c in rng.randint(0, _N_CATEGORIES,
                                                       rng.randint(1, 4)))),
                [int(t) for t in rng.randint(0, _TITLE_VOCAB,
                                             rng.randint(1, 6))])
            for i in range(1, _N_MOVIES + 1)}


def _reader(n, split):
    users = user_info()
    movies = movie_info()

    def reader():
        rng = common.seeded_rng("ml-" + split)
        for _ in range(n):
            uid = int(rng.randint(1, _N_USERS + 1))
            mid = int(rng.randint(1, _N_MOVIES + 1))
            _, gender, age, job = users[uid]
            _, cats, title = movies[mid]
            # rating correlated with (uid+mid) parity for learnability
            score = float(((uid * 31 + mid * 17) % 5) + 1)
            yield uid, gender, age, job, mid, cats, title, \
                np.array([score], np.float32)

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
