"""MovieLens-1M. reference: python/paddle/v2/dataset/movielens.py — rows of
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
score); plus max_*_id helpers the recommender book test uses.

When the real ``ml-1m.zip`` is present under ``<data_home>/movielens/``,
its ``users.dat / movies.dat / ratings.dat`` members are parsed
(``::``-separated, latin-1 titles): gender M/F -> 0/1, age mapped to its
``age_table`` index, category and title vocabularies built from the
corpus in sorted order, and a seeded 90/10 train/test split over rating
rows (the reference splits with a seeded ``random.random() < 0.1`` the
same way). The score is the raw 1-5 rating, like the synthetic corpus.
Otherwise the deterministic synthetic corpus below is used."""
from __future__ import annotations

import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "movie_categories", "user_info",
           "movie_info", "age_table"]

_N_USERS = 600
_N_MOVIES = 400
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 512
age_table = [1, 18, 25, 35, 45, 50, 56]

TRAIN_SIZE = 2048
TEST_SIZE = 256


_META = None


def _archive():
    return common.cached_file("movielens", "ml-1m.zip")


def _meta():
    """Parse the real archive once: (users, movies, ratings, cat_dict,
    title_dict) or None when only the synthetic corpus is available."""
    global _META
    zpath = _archive()
    if _META is not None and _META[0] == zpath:
        return _META[1]
    if not zpath:
        _META = (None, None)
        return None
    users, movies, cats, titles = {}, {}, {}, {}
    with zipfile.ZipFile(zpath) as z:
        def lines(name):
            for nm in z.namelist():
                if nm.endswith(name):
                    return z.read(nm).decode("latin-1").splitlines()
            raise ValueError("%s: no member ending in %r" % (zpath, name))

        for l in lines("users.dat"):
            uid, gender, age, job = l.strip().split("::")[:4]
            users[int(uid)] = (int(uid), 0 if gender == "M" else 1,
                              age_table.index(int(age)), int(job))
        # the reference strips the trailing "(year)" from each title
        # (re ^(.*)\((\d+)\)$ group 1) and lowercases title words before
        # building MOVIE_TITLE_DICT (movielens.py:106-127; its set
        # iteration order was arbitrary — sorted here for determinism)
        import re
        year_pat = re.compile(r"^(.*)\((\d+)\)$")
        raw_movies = []
        for l in lines("movies.dat"):
            mid, title, genres = l.strip().split("::")
            m = year_pat.match(title)
            if m:
                title = m.group(1)
            raw_movies.append((int(mid), title, genres.split("|")))
        for _, title, genres in raw_movies:
            for g in genres:
                cats.setdefault(g, None)
            for t in title.split():
                titles.setdefault(t.lower(), None)
        cat_dict = {g: i for i, g in enumerate(sorted(cats))}
        title_dict = {t: i for i, t in enumerate(sorted(titles))}
        for mid, title, genres in raw_movies:
            movies[mid] = (mid, sorted(cat_dict[g] for g in genres),
                           [title_dict[t.lower()] for t in title.split()])
        ratings = []
        for l in lines("ratings.dat"):
            uid, mid, score = l.strip().split("::")[:3]
            ratings.append((int(uid), int(mid), float(score)))
    _META = (zpath, (users, movies, ratings, cat_dict, title_dict))
    return _META[1]


def max_user_id():
    m = _meta()
    return max(m[0]) if m else _N_USERS


def max_movie_id():
    m = _meta()
    return max(m[1]) if m else _N_MOVIES


def max_job_id():
    m = _meta()
    return (max(u[3] for u in m[0].values()) if m else _N_JOBS - 1)


def movie_categories():
    m = _meta()
    return dict(m[3]) if m else {"<c%d>" % i: i
                                 for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    m = _meta()
    return dict(m[4]) if m else {"<t%d>" % i: i
                                 for i in range(_TITLE_VOCAB)}


def user_info():
    m = _meta()
    if m:
        return dict(m[0])
    rng = common.seeded_rng("ml-users")
    return {i: (i, int(rng.randint(0, 2)), int(rng.randint(0, len(age_table))),
                int(rng.randint(0, _N_JOBS)))
            for i in range(1, _N_USERS + 1)}


def movie_info():
    m = _meta()
    if m:
        return dict(m[1])
    rng = common.seeded_rng("ml-movies")
    return {i: (i,
                sorted(set(int(c) for c in rng.randint(0, _N_CATEGORIES,
                                                       rng.randint(1, 4)))),
                [int(t) for t in rng.randint(0, _TITLE_VOCAB,
                                             rng.randint(1, 6))])
            for i in range(1, _N_MOVIES + 1)}


def _reader(n, split):
    m = _meta()
    if m:
        def reader():
            users, movies, ratings = m[0], m[1], m[2]
            # seeded 90/10 split over rating rows, like the reference's
            # rand.random() < test_ratio with a fixed seed
            coin = common.seeded_rng("ml-split").rand(len(ratings))
            want_test = (split == "test")
            for (uid, mid, score), c in zip(ratings, coin):
                if (c < 0.1) != want_test:
                    continue
                _, gender, age, job = users[uid]
                _, cats, title = movies[mid]
                yield uid, gender, age, job, mid, cats, title, \
                    np.array([score], np.float32)

        return reader

    users = user_info()
    movies = movie_info()

    def reader():
        rng = common.seeded_rng("ml-" + split)
        for _ in range(n):
            uid = int(rng.randint(1, _N_USERS + 1))
            mid = int(rng.randint(1, _N_MOVIES + 1))
            _, gender, age, job = users[uid]
            _, cats, title = movies[mid]
            # rating correlated with (uid+mid) parity for learnability
            score = float(((uid * 31 + mid * 17) % 5) + 1)
            yield uid, gender, age, job, mid, cats, title, \
                np.array([score], np.float32)

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
