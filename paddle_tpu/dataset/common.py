"""Dataset infrastructure.

reference: python/paddle/v2/dataset/common.py (download cache under
~/.cache/paddle/dataset, md5 checks, cluster_files_reader, convert-to-recordio
helpers).

This environment has no network egress, so every dataset module generates a
*deterministic synthetic* corpus with the exact field types/shapes/vocab
structure of the real one (seeded per dataset). When the real files are
already present in the cache dir (placed there out of band), they are
parsed instead — every module carries a real-format parser matching the
reference's pipeline (mnist idx, cifar pickle-tar, uci_housing text,
imikolov ptb tgz, imdb aclImdb tar, movielens ml-1m zip, conll05
words/props gz, wmt14/wmt16 tgz, sentiment movie_reviews zip, flowers
jpg-tgz + .mat, voc2012 tar, mq2007 extracted LETOR text; each parser
is exercised by a real-format fixture test in
tests/test_data_pipeline.py). Only without the files is the synthetic
generator the source of truth for tests and benchmarks.
"""
from __future__ import annotations

import hashlib
import os

import numpy as np

def data_home():
    """Dataset cache dir, resolved at call time so both the env var and
    ``set_flags({'data_home': ...})`` take effect (env wins)."""
    env = os.environ.get("PADDLE_TPU_DATA_HOME")
    if env:
        return os.path.expanduser(env)
    from ..flags import FLAGS
    return os.path.expanduser(FLAGS.data_home)


# import-time snapshot kept for API parity (reference: v2/dataset/common.py
# DATA_HOME); prefer data_home() in new code
DATA_HOME = data_home()

__all__ = ["DATA_HOME", "md5file", "download", "seeded_rng",
           "synthetic_notice", "cached_file"]


def cached_file(module_name, filename):
    """Path of a real dataset file under the cache dir, or None. This is
    the switch between the real-format parsers and the synthetic
    generators: files are placed out of band (no egress here), named as
    the reference's download() would have cached them."""
    p = os.path.join(data_home(), module_name, filename)
    return p if os.path.exists(p) else None


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, retry_policy=None):
    """reference: v2/dataset/common.py download — here: cache-lookup only
    (zero egress); raises with a clear message if the file is absent.

    The lookup runs under a RetryPolicy (the resilience layer): on a
    cluster the cache dir is synced out of band, so a file that is
    missing or md5-torn NOW may be complete on the next attempt. The
    default budget retries ``PADDLE_TPU_DOWNLOAD_RETRIES`` times
    (default 1 = the old single-shot behavior); pass ``retry_policy``
    for full control. Each attempt crosses the ``dataset.download``
    fault site."""
    from ..resilience import RetryPolicy, RetryError, fault_point

    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(dirname, url.split("/")[-1])

    def attempt():
        fault_point("dataset.download")
        if os.path.exists(filename) and (not md5sum
                                         or md5file(filename) == md5sum):
            return filename
        raise RuntimeError(
            "dataset file %s is not cached and this environment has no "
            "network access; place the file under %s or use the synthetic "
            "reader (the default)" % (url, dirname))

    if retry_policy is None:
        attempts = max(int(os.environ.get("PADDLE_TPU_DOWNLOAD_RETRIES",
                                          "1")), 1)
        retry_policy = RetryPolicy(max_attempts=attempts, backoff=0.5,
                                   multiplier=2.0, max_backoff=10.0,
                                   name="dataset.download")
    try:
        return retry_policy.call(attempt)
    except RetryError as e:
        raise e.last


def seeded_rng(name):
    """Deterministic per-dataset generator."""
    seed = int.from_bytes(hashlib.md5(name.encode()).digest()[:4], "little")
    return np.random.RandomState(seed)


def synthetic_notice(mod):
    return ("%s: synthetic deterministic corpus (no network egress); "
            "field structure matches the reference dataset" % mod)


def cluster_files_reader(files_pattern, trainer_count, trainer_id):
    """Round-robin shard assignment: trainer i reads every file whose sort
    index % trainer_count == i (reference: v2/dataset/common.py
    cluster_files_reader — the static-sharding alternative to the
    fault-tolerant master dispatch). Yields unpickled samples written by
    ``convert``."""
    import glob
    import pickle

    from .. import native

    def reader():
        files = sorted(glob.glob(files_pattern))
        if not files:
            raise IOError("no files match %r" % files_pattern)
        for i, path in enumerate(files):
            if i % trainer_count != trainer_id:
                continue
            with native.Reader(path) as r:
                for rec in r:
                    yield pickle.loads(rec)

    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Serialize a reader's samples into sharded native recordio files.
    reference: v2/dataset/common.py convert (reader -> recordio shards the
    Go master partitions into tasks)."""
    import pickle

    from .. import native

    paths = []
    idx = 0
    w = None
    written = 0
    for sample in reader():
        if w is None:
            p = os.path.join(output_path,
                             "%s-%05d.rio" % (name_prefix, idx))
            os.makedirs(output_path, exist_ok=True)
            w = native.Writer(p)
            paths.append(p)
        w.write(pickle.dumps(sample))
        written += 1
        if written >= line_count:
            w.close()
            w = None
            written = 0
            idx += 1
    if w is not None:
        w.close()
    return paths
