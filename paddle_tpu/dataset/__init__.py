"""Datasets with the reference's reader APIs.

reference: python/paddle/v2/dataset/__init__.py (mnist, imikolov, imdb,
cifar, movielens, conll05, uci_housing, sentiment, wmt14, wmt16, mq2007,
flowers, voc2012). Each module exposes train()/test() creator functions
returning sample generators with the reference's field structure —
synthetic-deterministic here (see common.py).
"""
from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = ["mnist", "imikolov", "imdb", "cifar", "movielens", "conll05",
           "sentiment", "uci_housing", "wmt14", "wmt16", "mq2007", "flowers",
           "voc2012", "common"]
