"""WMT-16 en->de (multi-lingual API of the reference).
reference: python/paddle/v2/dataset/wmt16.py."""
from __future__ import annotations

from . import common

__all__ = ["train", "test", "validation", "get_dict"]

TRAIN_SIZE = 512
TEST_SIZE = 64


def get_dict(lang, dict_size, reverse=False):
    d = {"<w%d>" % i: i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _reader(n, split, src_dict_size, trg_dict_size):
    def reader():
        rng = common.seeded_rng("wmt16-" + split)
        for _ in range(n):
            slen = int(rng.randint(3, 15))
            src = [int(w) for w in rng.randint(3, src_dict_size, slen)]
            trg = [(w + 11) % (trg_dict_size - 3) + 3 for w in reversed(src)]
            yield src, [0] + trg, trg + [1]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(TRAIN_SIZE, "train", src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(TEST_SIZE, "test", src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(TEST_SIZE, "valid", src_dict_size, trg_dict_size)
