"""WMT-16 en->de (multi-lingual API of the reference).
reference: python/paddle/v2/dataset/wmt16.py.

When the real ``wmt16.tar.gz`` is present under ``<data_home>/wmt16/``,
its ``wmt16/{train,val,test}`` members are parsed the reference's way:
tab-separated en/de pairs, per-language vocabularies built from the
train member with <s>/<e>/<unk> as ids 0/1/2 then words by descending
frequency (ties alphabetical — the reference's py2 sort left tie order
unspecified), both sides wrapped <s>...<e> / start-next shifted. The
synthetic fallback below keeps its own deterministic corpus."""
from __future__ import annotations

import tarfile

from . import common

__all__ = ["train", "test", "validation", "get_dict"]

TRAIN_SIZE = 512
TEST_SIZE = 64

_MARKS = ("<s>", "<e>", "<unk>")


def _archive():
    return common.cached_file("wmt16", "wmt16.tar.gz")


_DICT_CACHE = {}


def _build_real_dict(tar_path, dict_size, lang):
    key = (tar_path, dict_size, lang)
    if key in _DICT_CACHE:
        return _DICT_CACHE[key]
    freq = {}
    col = 0 if lang == "en" else 1
    with tarfile.open(tar_path) as f:
        for line in f.extractfile("wmt16/train"):
            parts = line.decode("utf-8", "replace").strip().split("\t")
            if len(parts) != 2:
                continue
            for w in parts[col].split():
                freq[w] = freq.get(w, 0) + 1
    words = [w for w, _ in sorted(freq.items(),
                                  key=lambda t: (-t[1], t[0]))]
    d = {m: i for i, m in enumerate(_MARKS)}
    for w in words:
        if len(d) >= dict_size:
            break
        d[w] = len(d)
    _DICT_CACHE[key] = d
    return d


def get_dict(lang, dict_size, reverse=False):
    tar = _archive()
    d = (_build_real_dict(tar, dict_size, lang) if tar
         else {"<w%d>" % i: i for i in range(dict_size)})
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _real_reader(tar_path, member, src_dict_size, trg_dict_size,
                 src_lang):
    def reader():
        src_dict = _build_real_dict(tar_path, src_dict_size, src_lang)
        trg_dict = _build_real_dict(tar_path, trg_dict_size,
                                    "de" if src_lang == "en" else "en")
        start_id, end_id, unk_id = (src_dict[m] for m in _MARKS)
        src_col = 0 if src_lang == "en" else 1
        with tarfile.open(tar_path) as f:
            for line in f.extractfile(member):
                parts = line.decode("utf-8", "replace") \
                    .strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + [
                    src_dict.get(w, unk_id)
                    for w in parts[src_col].split()] + [end_id]
                trg_ids = [trg_dict.get(w, unk_id)
                           for w in parts[1 - src_col].split()]
                yield (src_ids, [start_id] + trg_ids,
                       trg_ids + [end_id])

    return reader


_REAL_MEMBERS = {"train": "wmt16/train", "test": "wmt16/test",
                 "valid": "wmt16/val"}


def _reader(n, split, src_dict_size, trg_dict_size, src_lang="en"):
    tar = _archive()
    if tar:
        return _real_reader(tar, _REAL_MEMBERS[split], src_dict_size,
                            trg_dict_size, src_lang)

    def reader():
        rng = common.seeded_rng("wmt16-" + split)
        for _ in range(n):
            slen = int(rng.randint(3, 15))
            src = [int(w) for w in rng.randint(3, src_dict_size, slen)]
            trg = [(w + 11) % (trg_dict_size - 3) + 3 for w in reversed(src)]
            yield src, [0] + trg, trg + [1]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(TRAIN_SIZE, "train", src_dict_size, trg_dict_size,
                   src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(TEST_SIZE, "test", src_dict_size, trg_dict_size,
                   src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader(TEST_SIZE, "valid", src_dict_size, trg_dict_size,
                   src_lang)
