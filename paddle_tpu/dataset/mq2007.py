"""MQ2007 learning-to-rank. reference: python/paddle/v2/dataset/mq2007.py —
pairwise mode yields (query_pos_features, query_neg_features), listwise
(label_list, feature_list); 46 features per doc."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test"]

N_FEATURES = 46
TRAIN_QUERIES = 128
TEST_QUERIES = 32


def _reader(n_queries, split, format):
    def reader():
        rng = common.seeded_rng("mq2007-" + split)
        w = common.seeded_rng("mq2007-w").normal(0, 1, N_FEATURES)
        for _ in range(n_queries):
            n_docs = int(rng.randint(2, 10))
            feats = rng.normal(0, 1, (n_docs, N_FEATURES)).astype(np.float32)
            scores = feats @ w + rng.normal(0, 0.1, n_docs)
            rels = np.digitize(scores, np.percentile(scores, [33, 66]))
            if format == "pairwise":
                for i in range(n_docs):
                    for j in range(n_docs):
                        if rels[i] > rels[j]:
                            yield feats[i], feats[j]
            else:
                yield [int(r) for r in rels], [f for f in feats]

    return reader


def train(format="pairwise"):
    return _reader(TRAIN_QUERIES, "train", format)


def test(format="pairwise"):
    return _reader(TEST_QUERIES, "test", format)
