"""MQ2007 learning-to-rank. reference: python/paddle/v2/dataset/mq2007.py —
pairwise mode yields (label [1], higher_doc [46], lower_doc [46]) per
C(n,2) pair with differing relevance; listwise yields
(relevance [n, 1], features [n, 46]) per query; 46 features per doc.

Real-data path: the reference downloads ``MQ2007.rar`` — a rar archive
this environment cannot unpack (no rarfile/unrar). Instead, the
*extracted* LETOR text files are consumed when present under
``<data_home>/mq2007/`` as ``Fold1/train.txt`` / ``Fold1/test.txt``
(the members the reference reads after extraction). Parsing follows the
reference: ``rel qid:N 1:v ... 46:v #comment`` lines, grouped by qid in
file order, queries whose relevance sums to zero filtered out, each
query list sorted by descending relevance before pair/list generation
(QueryList._correct_ranking_). The synthetic fallback generates the
same tuple shapes."""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test"]

N_FEATURES = 46
TRAIN_QUERIES = 128
TEST_QUERIES = 32


def _real_file(split):
    for rel in ("Fold1/%s.txt" % split,
                "MQ2007/Fold1/%s.txt" % split,
                "MQ2007/MQ2007/Fold1/%s.txt" % split):
        p = os.path.join(common.data_home(), "mq2007", rel)
        if os.path.exists(p):
            return p
    return None


def _load_querylists(path):
    lists, current, prev_qid = [], None, None
    with open(path) as f:
        for line in f:
            parts = line.split("#")[0].split()
            if len(parts) < 2 + N_FEATURES:
                continue
            rel = int(parts[0])
            qid = int(parts[1].split(":")[1])
            feat = np.array([float(p.split(":")[1])
                             for p in parts[2:2 + N_FEATURES]],
                            np.float32)
            if qid != prev_qid:
                if current:
                    lists.append(current)
                current, prev_qid = [], qid
            current.append((rel, feat))
    if current:
        lists.append(current)
    # query_filter: drop all-zero-relevance queries; _correct_ranking_:
    # sort each list by descending relevance (reference mq2007.py)
    out = []
    for ql in lists:
        if sum(r for r, _ in ql) != 0:
            out.append(sorted(ql, key=lambda t: -t[0]))
    return out


def _gen(querylists, format):
    for ql in querylists:
        if format == "pairwise":
            for i in range(len(ql)):
                for j in range(i + 1, len(ql)):
                    ri, fi = ql[i]
                    rj, fj = ql[j]
                    if ri > rj:
                        yield np.array([1]), fi, fj
                    elif ri < rj:
                        yield np.array([1]), fj, fi
        else:
            yield (np.array([[r] for r, _ in ql]),
                   np.array([f for _, f in ql]))


def _reader(n_queries, split, format):
    path = _real_file(split)
    if path:
        def reader():
            for row in _gen(_load_querylists(path), format):
                yield row

        return reader

    def reader():
        rng = common.seeded_rng("mq2007-" + split)
        w = common.seeded_rng("mq2007-w").normal(0, 1, N_FEATURES)
        for _ in range(n_queries):
            n_docs = int(rng.randint(2, 10))
            feats = rng.normal(0, 1, (n_docs, N_FEATURES)).astype(np.float32)
            scores = feats @ w + rng.normal(0, 0.1, n_docs)
            rels = np.digitize(scores, np.percentile(scores, [33, 66]))
            order = np.argsort(-rels)
            ql = [(int(rels[i]), feats[i]) for i in order]
            for row in _gen([ql], format):
                yield row

    return reader


def train(format="pairwise"):
    return _reader(TRAIN_QUERIES, "train", format)


def test(format="pairwise"):
    return _reader(TEST_QUERIES, "test", format)
