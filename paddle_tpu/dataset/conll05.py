"""CoNLL-2005 SRL. reference: python/paddle/v2/dataset/conll05.py — rows of
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, label_ids)
— 8 input sequences + BIO label sequence; get_dict()/get_embedding().

Real-data path: when ``wordDict.txt / verbDict.txt / targetDict.txt``
and ``conll05st-tests.tar.gz`` (the files the reference's download()
caches) are present under ``<data_home>/conll05/``, they are parsed
with the reference's exact pipeline — dict files line-number-indexed,
the label dict built as B-/I- pairs per tag plus O (tags iterated in
sorted order; the reference iterates a set, i.e. arbitrary order), the
props-file span notation converted to BIO, predicate context ±2 words
broadcast over the sentence, and the 5-token mark window. Like the
reference (whose training set is not public), train() reads the same
test.wsj corpus when real data is present. get_embedding() keeps the
array contract (the reference returns the raw downloaded file path),
sized to the active word dict."""
from __future__ import annotations

import gzip
import tarfile

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test", "train"]

UNK_IDX = 0

_DATA_TAR = "conll05st-tests.tar.gz"
_WORDS_MEMBER = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS_MEMBER = "conll05st-release/test.wsj/props/test.wsj.props.gz"


def _real_files():
    files = {n: common.cached_file("conll05", n) for n in
             ("wordDict.txt", "verbDict.txt", "targetDict.txt", _DATA_TAR)}
    return files if all(files.values()) else None


def _load_dict(path):
    with open(path) as f:
        return {l.strip(): i for i, l in enumerate(f)}


def _load_label_dict(path):
    tags = set()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("B-") or line.startswith("I-"):
                tags.add(line[2:])
    d = {}
    for tag in sorted(tags):
        d["B-" + tag] = len(d)
        d["I-" + tag] = len(d)
    d["O"] = len(d)
    return d


def _corpus_reader(tar_path):
    """Yield (sentence_words, predicate, BIO_labels) per predicate, the
    reference's span->BIO conversion verbatim."""
    def gen():
        with tarfile.open(tar_path) as tf:
            words_file = gzip.GzipFile(
                fileobj=tf.extractfile(_WORDS_MEMBER))
            props_file = gzip.GzipFile(
                fileobj=tf.extractfile(_PROPS_MEMBER))
            sentences, one_seg = [], []
            for word, label in zip(words_file, props_file):
                word = word.decode().strip()
                label = label.decode().strip().split()
                if not label:   # end of sentence
                    labels = [[x[i] for x in one_seg]
                              for i in range(len(one_seg[0]))] \
                        if one_seg else []
                    if labels:
                        verb_list = [x for x in labels[0] if x != "-"]
                        for i, lbl in enumerate(labels[1:]):
                            cur_tag, in_bracket, seq = "O", False, []
                            for l in lbl:
                                if l == "*" and not in_bracket:
                                    seq.append("O")
                                elif l == "*" and in_bracket:
                                    seq.append("I-" + cur_tag)
                                elif l == "*)":
                                    seq.append("I-" + cur_tag)
                                    in_bracket = False
                                elif "(" in l and ")" in l:
                                    cur_tag = l[1:l.find("*")]
                                    seq.append("B-" + cur_tag)
                                    in_bracket = False
                                elif "(" in l:
                                    cur_tag = l[1:l.find("*")]
                                    seq.append("B-" + cur_tag)
                                    in_bracket = True
                                else:
                                    raise RuntimeError(
                                        "Unexpected label: %s" % l)
                            yield sentences, verb_list[i], seq
                    sentences, one_seg = [], []
                else:
                    sentences.append(word)
                    one_seg.append(label)

    return gen


def _real_reader(files):
    word_dict = _load_dict(files["wordDict.txt"])
    verb_dict = _load_dict(files["verbDict.txt"])
    label_dict = _load_label_dict(files["targetDict.txt"])
    corpus = _corpus_reader(files[_DATA_TAR])

    def reader():
        for sentence, predicate, labels in corpus():
            sen_len = len(sentence)
            verb_index = labels.index("B-V")
            mark = [0] * sen_len
            ctx = {}
            for off, default in ((-2, "bos"), (-1, "bos"), (0, None),
                                 (1, "eos"), (2, "eos")):
                j = verb_index + off
                if 0 <= j < sen_len:
                    mark[j] = 1
                    ctx[off] = sentence[j]
                else:
                    ctx[off] = default
            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            ctxs = [[word_dict.get(ctx[o], UNK_IDX)] * sen_len
                    for o in (-2, -1, 0, 1, 2)]
            pred_idx = [verb_dict.get(predicate)] * sen_len
            label_idx = [label_dict.get(w) for w in labels]
            yield tuple([word_idx] + ctxs + [pred_idx, mark, label_idx])

    return reader

WORD_VOCAB = 4000
LABEL_KINDS = 30          # ~ 2*roles + O  (BIO over roles)
PRED_VOCAB = 300
TRAIN_SIZE = 256
TEST_SIZE = 64


def get_dict():
    files = _real_files()
    if files:
        return (_load_dict(files["wordDict.txt"]),
                _load_dict(files["verbDict.txt"]),
                _load_label_dict(files["targetDict.txt"]))
    word_dict = {"<w%d>" % i: i for i in range(WORD_VOCAB)}
    verb_dict = {"<v%d>" % i: i for i in range(PRED_VOCAB)}
    label_dict = {"<l%d>" % i: i for i in range(LABEL_KINDS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    files = _real_files()
    n = len(_load_dict(files["wordDict.txt"])) if files else WORD_VOCAB
    rng = common.seeded_rng("conll05-emb")
    return rng.normal(0, 0.1, (n, 32)).astype(np.float32)


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("conll05-" + split)
        for _ in range(n):
            length = int(rng.randint(4, 30))
            words = [int(w) for w in rng.randint(0, WORD_VOCAB, length)]
            verb_pos = int(rng.randint(0, length))
            verb = [int(rng.randint(0, PRED_VOCAB))] * length
            mark = [1 if i == verb_pos else 0 for i in range(length)]

            def ctx(off):
                return [words[min(max(i + off, 0), length - 1)]
                        for i in range(length)]

            # labels loosely depend on distance to the verb
            labels = [int((abs(i - verb_pos) * 2 + words[i]) % LABEL_KINDS)
                      for i in range(length)]
            yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2), verb,
                   mark, labels)

    return reader


def train():
    files = _real_files()
    if files:
        # the real CoNLL-05 training set is not public; the reference
        # trains on the test.wsj corpus too (conll05.py test() docstring)
        return _real_reader(files)
    return _reader(TRAIN_SIZE, "train")


def test():
    files = _real_files()
    if files:
        return _real_reader(files)
    return _reader(TEST_SIZE, "test")
