"""CoNLL-2005 SRL. reference: python/paddle/v2/dataset/conll05.py — rows of
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, label_ids)
— 8 input sequences + BIO label sequence; get_dict()/get_embedding()."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test", "train"]

WORD_VOCAB = 4000
LABEL_KINDS = 30          # ~ 2*roles + O  (BIO over roles)
PRED_VOCAB = 300
TRAIN_SIZE = 256
TEST_SIZE = 64


def get_dict():
    word_dict = {"<w%d>" % i: i for i in range(WORD_VOCAB)}
    verb_dict = {"<v%d>" % i: i for i in range(PRED_VOCAB)}
    label_dict = {"<l%d>" % i: i for i in range(LABEL_KINDS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = common.seeded_rng("conll05-emb")
    return rng.normal(0, 0.1, (WORD_VOCAB, 32)).astype(np.float32)


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("conll05-" + split)
        for _ in range(n):
            length = int(rng.randint(4, 30))
            words = [int(w) for w in rng.randint(0, WORD_VOCAB, length)]
            verb_pos = int(rng.randint(0, length))
            verb = [int(rng.randint(0, PRED_VOCAB))] * length
            mark = [1 if i == verb_pos else 0 for i in range(length)]

            def ctx(off):
                return [words[min(max(i + off, 0), length - 1)]
                        for i in range(length)]

            # labels loosely depend on distance to the verb
            labels = [int((abs(i - verb_pos) * 2 + words[i]) % LABEL_KINDS)
                      for i in range(length)]
            yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2), verb,
                   mark, labels)

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
