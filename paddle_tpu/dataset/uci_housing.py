"""UCI Housing. reference: python/paddle/v2/dataset/uci_housing.py — rows of
(features[13] float32 normalised, price[1] float32)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

TRAIN_SIZE = 404
TEST_SIZE = 102

# a fixed linear ground truth + noise so fit_a_line converges like the real
# dataset does
_rng = common.seeded_rng("uci-weights")
_W = _rng.normal(0.0, 1.0, 13).astype(np.float32)
_B = 22.5


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("uci-" + split)
        for _ in range(n):
            x = rng.normal(0.0, 1.0, 13).astype(np.float32)
            y = float(x @ _W + _B + rng.normal(0.0, 0.5))
            yield x, np.array([y], np.float32)

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
