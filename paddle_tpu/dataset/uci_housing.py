"""UCI Housing. reference: python/paddle/v2/dataset/uci_housing.py — rows of
(features[13] float32 normalised, price[1] float32).

When the real ``housing.data`` (the file the reference's download()
caches) is present under ``<data_home>/uci_housing/``, it is parsed and
normalised exactly as the reference does — per-feature
``(x - avg) / (max - min)`` computed over the whole corpus, then an
80/20 train/test split in file order (404/102 on the real 506 rows).
Otherwise a deterministic synthetic corpus with the same schema is
generated."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

TRAIN_SIZE = 404
TEST_SIZE = 102

# a fixed linear ground truth + noise so fit_a_line converges like the real
# dataset does
_rng = common.seeded_rng("uci-weights")
_W = _rng.normal(0.0, 1.0, 13).astype(np.float32)
_B = 22.5


def _load_real(path):
    data = np.loadtxt(path).astype(np.float32)
    if data.ndim != 2 or data.shape[1] != 14:
        raise ValueError("%s: expected 14 whitespace columns, got %s"
                         % (path, data.shape))
    # reference normalisation (v2/dataset/uci_housing.py feature_range):
    # (x - avg) / (max - min) per feature over the WHOLE corpus
    feats = data[:, :13]
    spread = feats.max(axis=0) - feats.min(axis=0)
    spread[spread == 0] = 1.0
    data[:, :13] = (feats - feats.mean(axis=0)) / spread
    return data


def _real_reader(path, split):
    def reader():
        data = _load_real(path)
        cut = int(len(data) * 0.8)
        rows = data[:cut] if split == "train" else data[cut:]
        for r in rows:
            yield r[:13], r[13:14].copy()

    return reader


def _reader(n, split):
    path = common.cached_file("uci_housing", "housing.data")
    if path:
        return _real_reader(path, split)

    def reader():
        rng = common.seeded_rng("uci-" + split)
        for _ in range(n):
            x = rng.normal(0.0, 1.0, 13).astype(np.float32)
            y = float(x @ _W + _B + rng.normal(0.0, 0.5))
            yield x, np.array([y], np.float32)

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
