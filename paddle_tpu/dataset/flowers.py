"""Oxford 102 flowers. reference: python/paddle/v2/dataset/flowers.py — rows
of (image [3*224*224] float32, label int in [0,102)).

When the real archives are present under ``<data_home>/flowers/``
(``102flowers.tgz`` + ``imagelabels.mat`` + ``setid.mat`` — the files
the reference's download() caches), they are parsed the reference's
way: split ids from setid.mat with the reference's deliberate swap
(train = ``tstid``, test = ``trnid`` — the "test" fold is the larger
one, per the comment at flowers.py:50), labels from imagelabels.mat
made 0-based, jpgs decoded + resized short-side 256 + center-cropped
224 + channel-reversed to BGR + mean-subtracted ([103.94, 116.78,
123.68], the reference's simple_transform defaults), flattened CHW
float32. Deviation: no random crop/flip on train (deterministic center
crop; the reference's train mapper randomises). Without the archives
the synthetic corpus below ([0,1] values, same shapes/labels) is used."""
from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

TRAIN_SIZE = 128
TEST_SIZE = 32
DIM = 3 * 224 * 224

# the reference's deliberate swap: tstid is the (larger) training fold
_FLAGS = {"train": "tstid", "test": "trnid", "valid": "valid"}
_MEAN_BGR = np.array([103.94, 116.78, 123.68], np.float32)


def _archives():
    files = {n: common.cached_file("flowers", n) for n in
             ("102flowers.tgz", "imagelabels.mat", "setid.mat")}
    return files if all(files.values()) else None


def _decode(blob):
    import io

    from PIL import Image
    im = Image.open(io.BytesIO(blob)).convert("RGB")
    w, h = im.size
    s = 256.0 / min(w, h)
    im = im.resize((max(int(round(w * s)), 256),
                    max(int(round(h * s)), 256)))
    w, h = im.size
    x0, y0 = (w - 224) // 2, (h - 224) // 2
    arr = np.asarray(im.crop((x0, y0, x0 + 224, y0 + 224)),
                     dtype=np.float32)           # HWC RGB
    arr = arr[:, :, ::-1] - _MEAN_BGR            # BGR, mean-subtracted
    return arr.transpose(2, 0, 1).reshape(-1)    # CHW flat


def _real_reader(files, split):
    def reader():
        import scipy.io as scio
        labels = scio.loadmat(files["imagelabels.mat"])["labels"][0]
        indexes = scio.loadmat(files["setid.mat"])[_FLAGS[split]][0]
        wanted = {"jpg/image_%05d.jpg" % i: int(labels[i - 1]) - 1
                  for i in indexes}
        with tarfile.open(files["102flowers.tgz"]) as tf:
            for m in tf.getmembers():
                if m.name in wanted:
                    yield (_decode(tf.extractfile(m).read()),
                           wanted[m.name])

    return reader


def _reader(n, split):
    files = _archives()
    if files:
        return _real_reader(files, split)

    def reader():
        rng = common.seeded_rng("flowers-" + split)
        for _ in range(n):
            label = int(rng.randint(0, 102))
            img = rng.uniform(0, 0.3, DIM).astype(np.float32)
            img[label * 100:(label + 1) * 100] += 0.6
            yield np.clip(img, 0, 1), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TRAIN_SIZE, "train")


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TEST_SIZE, "test")


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TEST_SIZE, "valid")
