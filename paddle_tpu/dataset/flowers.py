"""Oxford 102 flowers. reference: python/paddle/v2/dataset/flowers.py — rows
of (image [3*224*224] float32, label int in [0,102))."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

TRAIN_SIZE = 128
TEST_SIZE = 32
DIM = 3 * 224 * 224


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("flowers-" + split)
        for _ in range(n):
            label = int(rng.randint(0, 102))
            img = rng.uniform(0, 0.3, DIM).astype(np.float32)
            img[label * 100:(label + 1) * 100] += 0.6
            yield np.clip(img, 0, 1), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TRAIN_SIZE, "train")


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TEST_SIZE, "test")


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(TEST_SIZE, "valid")
