"""MNIST. reference: python/paddle/v2/dataset/mnist.py — rows of
(image[784] float32 in [-1, 1], label int in [0, 9])."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 2048   # synthetic corpus sizes (real: 60000/10000)
TEST_SIZE = 512


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("mnist-" + split)
        for i in range(n):
            label = int(rng.randint(0, 10))
            # blobs correlated with the label so models can actually learn
            img = rng.normal(-1.0, 0.3, 784).astype(np.float32)
            img[label * 70:(label + 1) * 70] += 1.5
            yield np.clip(img, -1.0, 1.0), label

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
