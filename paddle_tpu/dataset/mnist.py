"""MNIST. reference: python/paddle/v2/dataset/mnist.py — rows of
(image[784] float32 in [-1, 1], label int in [0, 9]).

When the real idx files (train-images-idx3-ubyte.gz etc., the names the
reference's download() caches) are present under ``<data_home>/mnist/``,
they are parsed; otherwise a deterministic synthetic corpus with the same
schema is generated."""
from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 2048   # synthetic corpus sizes (real: 60000/10000)
TEST_SIZE = 512

_FILES = {"train": ("train-images-idx3-ubyte.gz",
                    "train-labels-idx1-ubyte.gz"),
          "test": ("t10k-images-idx3-ubyte.gz",
                   "t10k-labels-idx1-ubyte.gz")}


def _open_maybe_gz(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _parse_idx_images(path):
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 0x803:
            raise ValueError("%s: bad idx3 magic 0x%x" % (path, magic))
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows * cols)


def _parse_idx_labels(path):
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 0x801:
            raise ValueError("%s: bad idx1 magic 0x%x" % (path, magic))
        return np.frombuffer(f.read(n), dtype=np.uint8)


def _real_reader(img_path, lbl_path):
    def reader():
        imgs = _parse_idx_images(img_path)
        lbls = _parse_idx_labels(lbl_path)
        for im, lb in zip(imgs, lbls):
            # the reference normalizes to [-1, 1] (v2/dataset/mnist.py)
            yield (im.astype(np.float32) / 255.0 * 2.0 - 1.0), int(lb)

    return reader


def _reader(n, split):
    img_gz, lbl_gz = _FILES[split]
    img_p = (common.cached_file("mnist", img_gz)
             or common.cached_file("mnist", img_gz[:-3]))
    lbl_p = (common.cached_file("mnist", lbl_gz)
             or common.cached_file("mnist", lbl_gz[:-3]))
    if img_p and lbl_p:
        return _real_reader(img_p, lbl_p)

    def reader():
        rng = common.seeded_rng("mnist-" + split)
        for i in range(n):
            label = int(rng.randint(0, 10))
            # blobs correlated with the label so models can actually learn
            img = rng.normal(-1.0, 0.3, 784).astype(np.float32)
            img[label * 70:(label + 1) * 70] += 1.5
            yield np.clip(img, -1.0, 1.0), label

    return reader


def train():
    return _reader(TRAIN_SIZE, "train")


def test():
    return _reader(TEST_SIZE, "test")
