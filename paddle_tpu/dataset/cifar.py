"""CIFAR-10/100. reference: python/paddle/v2/dataset/cifar.py — rows of
(image[3072] float32 in [0, 1], label int).

Real data: the reference caches ``cifar-10-python.tar.gz`` /
``cifar-100-python.tar.gz`` (pickled batches of {data: [N,3072] u8,
labels/fine_labels: [N]}); when present under ``<data_home>/cifar/`` they
are parsed, else the synthetic corpus is generated."""
from __future__ import annotations

import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]

TRAIN_SIZE = 1024
TEST_SIZE = 256


def _real_reader(tar_path, classes, split):
    sub = "data_batch" if split == "train" else "test_batch"
    if classes == 100:
        sub = "train" if split == "train" else "test"
    key = b"labels" if classes == 10 else b"fine_labels"

    def reader():
        with tarfile.open(tar_path, mode="r") as tar:
            members = sorted(m.name for m in tar.getmembers()
                             if sub in m.name and m.name.find(".") == -1)
            for name in members:
                batch = pickle.load(tar.extractfile(name),
                                    encoding="bytes")
                for im, lb in zip(batch[b"data"], batch[key]):
                    # reference normalizes to [0, 1] (v2/dataset/cifar.py)
                    yield im.astype(np.float32) / 255.0, int(lb)

    return reader


def _reader(n, classes, split):
    tar_name = ("cifar-10-python.tar.gz" if classes == 10
                else "cifar-100-python.tar.gz")
    tar_path = common.cached_file("cifar", tar_name)
    if tar_path:
        return _real_reader(tar_path, classes, split)

    def reader():
        rng = common.seeded_rng("cifar%d-%s" % (classes, split))
        per = 3072 // classes if classes <= 3072 else 1
        for i in range(n):
            label = int(rng.randint(0, classes))
            img = rng.uniform(0.0, 0.4, 3072).astype(np.float32)
            img[label * per:(label + 1) * per] += 0.5
            yield np.clip(img, 0.0, 1.0), label

    return reader


def train10():
    return _reader(TRAIN_SIZE, 10, "train")


def test10():
    return _reader(TEST_SIZE, 10, "test")


def train100():
    return _reader(TRAIN_SIZE, 100, "train")


def test100():
    return _reader(TEST_SIZE, 100, "test")
