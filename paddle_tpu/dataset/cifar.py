"""CIFAR-10/100. reference: python/paddle/v2/dataset/cifar.py — rows of
(image[3072] float32 in [0, 1], label int)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]

TRAIN_SIZE = 1024
TEST_SIZE = 256


def _reader(n, classes, split):
    def reader():
        rng = common.seeded_rng("cifar%d-%s" % (classes, split))
        per = 3072 // classes if classes <= 3072 else 1
        for i in range(n):
            label = int(rng.randint(0, classes))
            img = rng.uniform(0.0, 0.4, 3072).astype(np.float32)
            img[label * per:(label + 1) * per] += 0.5
            yield np.clip(img, 0.0, 1.0), label

    return reader


def train10():
    return _reader(TRAIN_SIZE, 10, "train")


def test10():
    return _reader(TEST_SIZE, 10, "test")


def train100():
    return _reader(TRAIN_SIZE, 100, "train")


def test100():
    return _reader(TEST_SIZE, 100, "test")
