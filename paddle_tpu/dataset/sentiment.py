"""NLTK movie-review sentiment. reference:
python/paddle/v2/dataset/sentiment.py — rows of (word_ids, label 0/1).

When the real NLTK corpus zip (``movie_reviews.zip``) is present under
``<data_home>/sentiment/``, it is parsed the reference's way: word dict
over the whole corpus by descending frequency (ties alphabetical; the
reference's py2 cmp-sort left tie order unspecified), files interleaved
neg/pos (label 0 = neg, 1 = pos, from the path like the reference's
``0 if 'neg' in sample_file``), first 80% of the interleaved list is
train, the rest test (the reference hardcodes 1600/400 of its fixed
2000 files — the same 80/20 ratio). The corpus files are pre-tokenized,
so whitespace splitting matches NLTK's reader on this corpus. Without
the zip, the synthetic IMDB-style corpus below is used."""
from __future__ import annotations

import zipfile

from . import common, imdb

__all__ = ["get_word_dict", "train", "test"]


def _archive():
    return common.cached_file("sentiment", "movie_reviews.zip")


def _files(z, pol):
    return sorted(n for n in z.namelist()
                  if ("movie_reviews/%s/" % pol) in n
                  and n.endswith(".txt"))


def _tokens(z, name):
    return z.read(name).decode("utf-8", "replace").lower().split()


_DICT_CACHE = {}


def get_word_dict():
    zpath = _archive()
    if not zpath:
        return imdb.word_dict()
    if zpath in _DICT_CACHE:
        return _DICT_CACHE[zpath]
    freq = {}
    with zipfile.ZipFile(zpath) as z:
        for pol in ("neg", "pos"):
            for name in _files(z, pol):
                for w in _tokens(z, name):
                    freq[w] = freq.get(w, 0) + 1
    kept = sorted(freq.items(), key=lambda t: (-t[1], t[0]))
    _DICT_CACHE[zpath] = {w: i for i, (w, _) in enumerate(kept)}
    return _DICT_CACHE[zpath]


def _real_reader(split):
    zpath = _archive()
    wd = get_word_dict()   # cached: built once, not once per epoch

    def reader():
        with zipfile.ZipFile(zpath) as z:
            neg, pos = _files(z, "neg"), _files(z, "pos")
            interleaved = [f for pair in zip(neg, pos) for f in pair]
            cut = int(len(interleaved) * 0.8)
            part = interleaved[:cut] if split == "train" \
                else interleaved[cut:]
            for name in part:
                label = 0 if "/neg/" in name else 1
                yield [wd[w] for w in _tokens(z, name)], label

    return reader


def train():
    if _archive():
        return _real_reader("train")
    return imdb._reader(512, "sent-train")


def test():
    if _archive():
        return _real_reader("test")
    return imdb._reader(128, "sent-test")
