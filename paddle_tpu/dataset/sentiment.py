"""NLTK movie-review sentiment. reference:
python/paddle/v2/dataset/sentiment.py — rows of (word_ids, label 0/1)."""
from __future__ import annotations

from . import common, imdb

__all__ = ["get_word_dict", "train", "test"]


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb._reader(512, "sent-train")


def test():
    return imdb._reader(128, "sent-test")
