"""IMDB sentiment. reference: python/paddle/v2/dataset/imdb.py — rows of
(word_id_sequence, label 0/1); word_dict() maps token -> id."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "word_dict"]

VOCAB = 5147          # mimic a realistic small vocab
TRAIN_SIZE = 1024
TEST_SIZE = 256

_POS_WORDS = None


def word_dict():
    return {"<w%d>" % i: i for i in range(VOCAB)}


def _pos_words():
    global _POS_WORDS
    if _POS_WORDS is None:
        rng = common.seeded_rng("imdb-poswords")
        _POS_WORDS = set(int(w) for w in rng.choice(VOCAB, 400,
                                                    replace=False))
    return _POS_WORDS


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("imdb-" + split)
        pos = _pos_words()
        pos_arr = np.array(sorted(pos))
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 120))
            words = rng.randint(0, VOCAB, length)
            if label == 1:  # positive reviews use positive words more
                k = max(1, length // 3)
                idx = rng.choice(length, k, replace=False)
                words[idx] = pos_arr[rng.randint(0, len(pos_arr), k)]
            yield [int(w) for w in words], label

    return reader


def train(word_idx=None):
    return _reader(TRAIN_SIZE, "train")


def test(word_idx=None):
    return _reader(TEST_SIZE, "test")
