"""IMDB sentiment. reference: python/paddle/v2/dataset/imdb.py — rows of
(word_id_sequence, label 0/1); word_dict() maps token -> id.

When the real ``aclImdb_v1.tar.gz`` is present under
``<data_home>/imdb/``, it is parsed the reference's way: reviews under
``aclImdb/{split}/{pos,neg}/*.txt``, punctuation stripped + lowercased
tokens, vocabulary sorted by (-freq, word) over all four splits with
``<unk>`` appended last, and — matching the reference's label
convention — **pos = 0, neg = 1**. The synthetic fallback keeps its own
(documented) 1 = positive convention; code that learns a binary
classifier is agnostic either way."""
from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "word_dict"]

VOCAB = 5147          # mimic a realistic small vocab
TRAIN_SIZE = 1024
TEST_SIZE = 256

_POS_WORDS = None


def _archive():
    return common.cached_file("imdb", "aclImdb_v1.tar.gz")


def _tokenize(blob):
    txt = blob.decode("utf-8", "replace").lower()
    return txt.translate(str.maketrans("", "", string.punctuation)).split()


def _real_docs(tar_path, pattern):
    pat = re.compile(pattern)
    with tarfile.open(tar_path) as tf:
        for m in tf.getmembers():
            if bool(pat.match(m.name)):
                yield _tokenize(tf.extractfile(m).read())


_DICT_CACHE = {}


def word_dict():
    tar = _archive()
    if tar:
        if tar in _DICT_CACHE:
            return _DICT_CACHE[tar]
        freq = {}
        # one pass over the tar: each _real_docs call re-decompresses
        # the whole gz stream, so the four split/polarity corpora are
        # matched with a single combined pattern
        for toks in _real_docs(
                tar, r".*aclImdb/(train|test)/(pos|neg)/.*\.txt$"):
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
        kept = sorted(freq.items(), key=lambda t: (-t[1], t[0]))
        d = {w: i for i, (w, _) in enumerate(kept)}
        d["<unk>"] = len(d)
        _DICT_CACHE[tar] = d
        return d
    return {"<w%d>" % i: i for i in range(VOCAB)}


def _pos_words():
    global _POS_WORDS
    if _POS_WORDS is None:
        rng = common.seeded_rng("imdb-poswords")
        _POS_WORDS = set(int(w) for w in rng.choice(VOCAB, 400,
                                                    replace=False))
    return _POS_WORDS


def _reader(n, split):
    def reader():
        rng = common.seeded_rng("imdb-" + split)
        pos = _pos_words()
        pos_arr = np.array(sorted(pos))
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 120))
            words = rng.randint(0, VOCAB, length)
            if label == 1:  # positive reviews use positive words more
                k = max(1, length // 3)
                idx = rng.choice(length, k, replace=False)
                words[idx] = pos_arr[rng.randint(0, len(pos_arr), k)]
            yield [int(w) for w in words], label

    return reader


def _real_reader(split, word_idx):
    tar = _archive()

    def reader():
        wd = word_idx if word_idx is not None else word_dict()
        unk = wd.get("<unk>", len(wd) - 1)
        # reference label convention: pos = 0, neg = 1
        for label, pol in ((0, "pos"), (1, "neg")):
            for toks in _real_docs(
                    tar, r".*aclImdb/%s/%s/.*\.txt$" % (split, pol)):
                yield [wd.get(w, unk) for w in toks], label

    return reader


def train(word_idx=None):
    if _archive():
        return _real_reader("train", word_idx)
    return _reader(TRAIN_SIZE, "train")


def test(word_idx=None):
    if _archive():
        return _real_reader("test", word_idx)
    return _reader(TEST_SIZE, "test")
