"""Unique name generator (reference: python/paddle/fluid/framework.py:unique_name
via paddle/fluid/pybind ``unique_integer``). Thread-unsafe by design: program
construction is single-threaded Python, like the reference."""
from __future__ import annotations

import collections
import contextlib

_counters: dict = collections.defaultdict(int)


def generate(key: str) -> str:
    _counters[key] += 1
    return "%s_%d" % (key, _counters[key] - 1)


@contextlib.contextmanager
def guard(new_state=None):
    """Reset the namespace (used by tests to make programs reproducible)."""
    global _counters
    old = _counters
    _counters = collections.defaultdict(int) if new_state is None else new_state
    try:
        yield
    finally:
        _counters = old
