"""Canonical Program serialization: the v1 protostr contract, TPU-shape.

reference: python/paddle/trainer/config_parser.py:4350 (parse_config ->
ModelConfig proto) and the golden-protostr tests under
python/paddle/trainer_config_helpers/tests/configs/ — the v1 stack treats
the config as DATA: a topology can be dumped, diffed, and reloaded.
Program-as-config keeps that contract here: ``program_to_dict`` walks the
blocks into a stable, JSON-serializable structure, ``program_to_protostr``
renders it canonically (sorted keys, fixed indent — the protostr analog),
and ``program_from_dict`` rebuilds an executable Program. Round-trip
identity (build -> dump -> load -> run matches) is tested in
tests/test_config_serialization.py against committed golden fixtures.
"""
from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from . import ir

__all__ = ["program_to_dict", "program_from_dict", "program_to_protostr",
           "program_from_protostr"]

_FORMAT_VERSION = 1


def _attr_to_json(v):
    if isinstance(v, ir.Block):
        return {"__block__": v.idx}
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, tuple):
        return [_attr_to_json(x) for x in v]
    if isinstance(v, list):
        return [_attr_to_json(x) for x in v]
    if isinstance(v, dict):
        return {k: _attr_to_json(x) for k, x in sorted(v.items())}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise TypeError(
        "op attr %r (%s) is not serializable — extend serialize.py if a "
        "new attr kind is introduced" % (v, type(v).__name__))


def _attr_from_json(v, program):
    if isinstance(v, dict):
        if "__block__" in v:
            return program.blocks[v["__block__"]]
        if "__ndarray__" in v:
            return np.asarray(v["__ndarray__"], dtype=v["dtype"])
        return {k: _attr_from_json(x, program) for k, x in v.items()}
    if isinstance(v, list):
        return [_attr_from_json(x, program) for x in v]
    return v


def _var_to_json(v: ir.Variable) -> Dict[str, Any]:
    d = {
        "name": v.name,
        "shape": list(v.shape) if v.shape is not None else None,
        "dtype": str(getattr(v.dtype, "name", v.dtype)),
        "lod_level": v.lod_level,
        "persistable": bool(v.persistable),
        "stop_gradient": bool(v.stop_gradient),
        "type": getattr(v.type, "name", str(v.type)),
    }
    if isinstance(v, ir.Parameter):
        d["is_parameter"] = True
        d["trainable"] = bool(v.trainable)
        if v.optimize_attr and v.optimize_attr != {"learning_rate": 1.0}:
            d["optimize_attr"] = _attr_to_json(v.optimize_attr)
    if getattr(v, "is_data", False):
        d["is_data"] = True
    return d


def program_to_dict(program: ir.Program) -> Dict[str, Any]:
    """Stable, JSON-clean structure of the whole program (all blocks,
    vars sorted by name, ops in execution order)."""
    blocks = []
    for blk in program.blocks:
        blocks.append({
            "idx": blk.idx,
            "parent_idx": blk.parent_idx,
            "vars": [_var_to_json(v)
                     for _, v in sorted(blk.vars.items())],
            "ops": [{
                "type": op.type,
                "inputs": {s: list(ns)
                           for s, ns in sorted(op.inputs.items())},
                "outputs": {s: list(ns)
                            for s, ns in sorted(op.outputs.items())},
                "attrs": {k: _attr_to_json(v)
                          for k, v in sorted(op.attrs.items())},
            } for op in blk.ops],
        })
    d = {"format_version": _FORMAT_VERSION, "blocks": blocks}
    if program._seed is not None:
        d["random_seed"] = program._seed
    if getattr(program, "_data_vars_order", None):
        d["data_vars_order"] = [v.name
                                for v in program._data_vars_order]
    return d


def program_from_dict(d: Dict[str, Any]) -> ir.Program:
    """Rebuild an executable Program from ``program_to_dict`` output."""
    if d.get("format_version") != _FORMAT_VERSION:
        raise ValueError("unsupported program format %r"
                         % d.get("format_version"))
    program = ir.Program()
    # materialize every block first so BLOCK attrs can resolve
    for bd in d["blocks"][1:]:
        blk = ir.Block(program, bd["idx"], bd["parent_idx"])
        program.blocks.append(blk)
    for bd in d["blocks"]:
        blk = program.blocks[bd["idx"]]
        for vd in bd["vars"]:
            from .types import VarType
            vtype = VarType[vd["type"]] if vd["type"] in \
                VarType.__members__ else vd["type"]
            kwargs = dict(shape=vd["shape"], dtype=vd["dtype"],
                          lod_level=vd["lod_level"],
                          persistable=vd["persistable"],
                          stop_gradient=vd["stop_gradient"],
                          type=vtype, name=vd["name"])
            if vd.get("is_parameter"):
                v = ir.Parameter(blk, kwargs.pop("shape"),
                                 kwargs.pop("dtype"),
                                 trainable=vd.get("trainable", True),
                                 **kwargs)
                if "optimize_attr" in vd:
                    v.optimize_attr = dict(vd["optimize_attr"])
            else:
                v = ir.Variable(blk, **kwargs)
            if vd.get("is_data"):
                v.is_data = True
            blk.vars[v.name] = v
        for od in bd["ops"]:
            op = ir.Operator(blk, od["type"], None, None, None)
            op.inputs = {s: list(ns) for s, ns in od["inputs"].items()}
            op.outputs = {s: list(ns) for s, ns in od["outputs"].items()}
            op.attrs = {k: _attr_from_json(v, program)
                        for k, v in od["attrs"].items()}
            blk.ops.append(op)
            for ns in op.outputs.values():
                for n in ns:
                    v = blk._find_var_recursive(n)
                    if v is not None:
                        v.op = op
    if "random_seed" in d:
        program._seed = d["random_seed"]
    if "data_vars_order" in d:
        gb = program.global_block()
        program._data_vars_order = [
            gb._find_var_recursive(n) for n in d["data_vars_order"]]
    program._bump_version()
    return program


def program_to_protostr(program: ir.Program) -> str:
    """Canonical text rendering — the protostr-golden-file analog
    (reference: trainer_config_helpers/tests/configs/protostr/*)."""
    return json.dumps(program_to_dict(program), sort_keys=True, indent=1)


def program_from_protostr(text: str) -> ir.Program:
    return program_from_dict(json.loads(text))
