"""Variable kinds and dtype system for the TPU-native framework.

Mirrors the role of the reference's ``VarType`` proto enum
(reference: paddle/fluid/framework/framework.proto:101-135) and the fp16
support (reference: paddle/fluid/platform/float16.h:71) — here bfloat16 is the
first-class reduced precision type because the MXU natively consumes bf16.
"""
from __future__ import annotations

import enum

import numpy as np

try:  # jax ships ml_dtypes
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    bfloat16 = np.dtype("float32")


class VarType(enum.Enum):
    """Kinds of variables a Block can hold.

    reference: paddle/fluid/framework/framework.proto:101-135 (17 kinds).
    The TPU build keeps the ones that survive the XLA-native redesign;
    CHANNEL/PLACE_LIST die (host async replaces CSP), READER becomes the
    reader stack in ``paddle_tpu.reader``.
    """

    LOD_TENSOR = 1        # dense array, optionally with LoD (ragged) metadata
    SELECTED_ROWS = 2     # sparse row-subset gradient (embedding grads)
    LOD_TENSOR_ARRAY = 3  # list of LoDTensors (dynamic RNN outputs)
    LOD_RANK_TABLE = 4    # sequences sorted by length (dynamic RNN batching)
    STEP_SCOPES = 5       # control-flow bookkeeping (kept for API parity)
    FETCH_LIST = 6
    FEED_MINIBATCH = 7
    READER = 8
    RAW = 9               # arbitrary host object


# Canonical dtype registry: string name -> numpy dtype.
_DTYPES = {
    "float32": np.dtype("float32"),
    "float64": np.dtype("float64"),
    "float16": np.dtype("float16"),
    "bfloat16": bfloat16,
    "int8": np.dtype("int8"),
    "uint8": np.dtype("uint8"),
    "int16": np.dtype("int16"),
    "int32": np.dtype("int32"),
    "int64": np.dtype("int64"),
    "bool": np.dtype("bool"),
}


def convert_dtype(dtype) -> np.dtype:
    """Normalise any dtype spec (str | np.dtype | jnp dtype) to np.dtype."""
    if dtype is None:
        return _DTYPES["float32"]
    if isinstance(dtype, str):
        if dtype in _DTYPES:
            return _DTYPES[dtype]
        return np.dtype(dtype)
    return np.dtype(dtype)


def is_floating(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in (
        _DTYPES["float32"],
        _DTYPES["float64"],
        _DTYPES["float16"],
        _DTYPES["bfloat16"],
    )
