"""LoDTensor: ragged nested-sequence tensor, the reference's signature feature.

reference: paddle/fluid/framework/lod_tensor.h:49,101 — a dense tensor plus
"level of detail" offsets describing nested variable-length sequences, so a
minibatch of ragged sequences is stored concatenated with no padding.

TPU-first redesign: XLA wants static shapes, so the device-side currency is
(dense data, int32 offset vectors) where the offset vectors are themselves
ordinary arrays traced through the program. Host-side, ``LoDTensor`` keeps the
reference's API (``lod``/``recursive_sequence_lengths``); sequence ops lower
to segment reductions (jax.ops.segment_sum et al.) driven by segment-ids
computed from the offsets. Distinct (total_tokens, num_seqs) shapes hit the
executor compile cache separately — bucketing at feed time (see
``paddle_tpu.reader.bucket``) bounds the number of compilations.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np


class LoDTensor(object):
    def __init__(self, data=None, lod: Sequence[Sequence[int]] = None):
        self._data = None if data is None else np.asarray(data)
        self._lod: List[List[int]] = [list(l) for l in lod] if lod else []

    # -- reference-parity API ------------------------------------------------
    def set(self, array, place=None):
        self._data = np.asarray(array)

    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self) -> List[List[int]]:
        return self._lod

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = [lengths_to_offsets(l) for l in lengths]

    def recursive_sequence_lengths(self):
        return [offsets_to_lengths(l) for l in self._lod]

    def has_valid_recursive_sequence_lengths(self) -> bool:
        if not self._lod:
            return True
        for level, offs in enumerate(self._lod):
            if not offs or offs[0] != 0 or any(b < a for a, b in zip(offs, offs[1:])):
                return False
            nxt = (self._lod[level + 1] if level + 1 < len(self._lod)
                   else list(range(self.shape[0] + 1)) if self._data is not None else None)
            if nxt is not None and offs[-1] != len(nxt) - 1:
                return False
        return True

    def numpy(self) -> np.ndarray:
        return self._data

    def __array__(self, dtype=None):
        return self._data if dtype is None else self._data.astype(dtype)

    @property
    def shape(self):
        return self._data.shape if self._data is not None else None

    @property
    def dtype(self):
        return self._data.dtype if self._data is not None else None

    @property
    def lod_level(self):
        return len(self._lod)

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.shape, self._lod)


# -- offset/length/segment-id conversions ------------------------------------

def lengths_to_offsets(lengths) -> List[int]:
    offs = [0]
    for l in lengths:
        offs.append(offs[-1] + int(l))
    return offs


def offsets_to_lengths(offsets) -> List[int]:
    return [int(b - a) for a, b in zip(offsets, offsets[1:])]


def offsets_to_segment_ids(offsets, total=None) -> np.ndarray:
    """[0,2,5] -> [0,0,1,1,1]; the device-side form sequence ops consume."""
    offsets = np.asarray(offsets, dtype=np.int64)
    total = int(offsets[-1]) if total is None else total
    ids = np.zeros(total, dtype=np.int32)
    np.add.at(ids, offsets[1:-1], 1)
    return np.cumsum(ids).astype(np.int32)


def build_lod_tensor(data_list, place=None) -> LoDTensor:
    """Concatenate a python list of per-sequence arrays into one LoDTensor.

    reference: python/paddle/fluid/data_feeder.py:118 (DataToLoDTensorConverter)
    and lod_tensor.md's create_lod_tensor.
    """
    arrays = [np.asarray(a) for a in data_list]
    lengths = [a.shape[0] for a in arrays]
    t = LoDTensor(np.concatenate(arrays, axis=0) if arrays else np.zeros((0,)),
                  [lengths_to_offsets(lengths)])
    return t
