"""Executor: lowers a Program block to ONE jitted XLA computation.

The reference interprets blocks op-by-op in C++ — create vars, then
``for op in block.ops: op->Run(scope, place)``
(reference: paddle/fluid/framework/executor.cc:39-69,125-144), with feed/fetch
ops spliced per call (executor.cc:236-313) and pybind crossing per run.

TPU-first inversion: ``Executor.run(program, feed, fetch_list)`` symbolically
*traces* the block — each op's registered jax lowering consumes traced values
from an environment — producing a pure function
``(state, feed, rng) -> (fetches, state')`` which is jit-compiled once per
(program version, feed signature) and cached. Parameters are donated device
buffers; the per-op interpreter loop, runtime InferShape, and DataTransform
(reference: operator.cc:495-572) all disappear into XLA fusion. An eager mode
(``use_jit=False`` or programs containing host-only ops like save/load) runs
the same lowerings op-by-op — that *is* the reference executor semantics,
kept as the debug path.
"""
from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import ir, registry
from .lod import LoDTensor, lengths_to_offsets, offsets_to_lengths

_LOG = logging.getLogger("paddle_tpu.executor")
from .scope import Scope, global_scope

RNG_VAR = "@RNG_KEY@"


class TracedLoD(object):
    """Device-side ragged value: dense data + per-level int32 offset arrays.

    The traced analog of LoDTensor (reference: lod_tensor.h:101); offsets ride
    through jit as ordinary arrays so sequence ops can rebuild segment ids.

    ``max_lens`` is the static (host-known, per lod level) maximum sequence
    length, captured at feed time. It is what lets scan-based sequence ops
    (dynamic_lstm/gru, sequence_conv, crf…) pad the ragged batch to a fixed
    [num_seqs, max_len, ...] layout inside jit — the TPU-native replacement
    for the reference's sequence2batch reordering
    (reference: operators/math/sequence2batch.h, cuda hl_sequence.h:70).
    Distinct max_lens re-specialise the compile cache; bucketing at the
    reader bounds how many.
    """

    def __init__(self, data, lod=(), max_lens=None):
        self.data = data
        self.lod = tuple(lod)  # tuple of 1-D int32 offset arrays
        self.max_lens = (tuple(max_lens) if max_lens is not None
                         else (None,) * len(self.lod))


jax.tree_util.register_pytree_node(
    TracedLoD,
    lambda t: (((t.data,) + t.lod), t.max_lens),
    lambda aux, ch: TracedLoD(ch[0], ch[1:], max_lens=aux))


class ConcreteScalar(object):
    """A scalar whose *value* is known at trace time, riding alongside its
    traced array form.

    The dynamic-control-flow machinery (While counters, array indices, loop
    conditions, max-sequence-len bounds) needs concrete Python values while
    the surrounding program is being jit-traced — this is how the reference's
    force_cpu loop counters (fill_constant force_cpu=True; while_op.cc reads
    the condition on host) map onto XLA tracing: the counter arithmetic
    happens at trace time (unrolling the loop into the graph), everything
    else stays traced. Ops that understand it (increment, compare ops,
    while, array read/write) propagate the concrete value; everything else
    sees the ``data`` array via raw_data()."""

    __slots__ = ("value", "data")

    def __init__(self, value, data=None):
        self.value = value
        self.data = (data if data is not None
                     else jnp.asarray([value]))

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def __repr__(self):
        return "ConcreteScalar(%r)" % (self.value,)


jax.tree_util.register_pytree_node(
    ConcreteScalar,
    lambda c: ((c.data,), c.value),
    lambda aux, ch: ConcreteScalar(aux, ch[0]))


def concrete_value(v):
    """Python value of ``v`` if known at trace time, else None."""
    if isinstance(v, ConcreteScalar):
        return v.value
    return None


def raw_data(v):
    if isinstance(v, TracedLoD):
        return v.data
    if isinstance(v, ConcreteScalar):
        return v.data
    return v


def with_lod_of(v, data):
    """Wrap ``data`` with the lod of ``v`` (sequence-preserving elementwise ops)."""
    if isinstance(v, TracedLoD) and v.lod:
        return TracedLoD(data, v.lod, max_lens=v.max_lens)
    return data


class RngSource(object):
    """Threads a PRNG key through a trace; each draw splits deterministically."""

    def __init__(self, key):
        self.key = key
        self.used = False

    def next(self):
        self.used = True
        self.key, sub = jax.random.split(self.key)
        return sub


class LowerContext(object):
    """What an op lowering sees: traced inputs, attrs, output setter, RNG."""

    __slots__ = ("op", "env", "rng", "block", "value_hook")

    def __init__(self, op: ir.Operator, env: Dict[str, Any], rng: RngSource,
                 block: ir.Block, value_hook=None):
        self.op = op
        self.env = env
        self.rng = rng
        self.block = block
        self.value_hook = value_hook

    # inputs -----------------------------------------------------------------
    def input(self, slot, idx=0):
        names = self.op.input(slot)
        if len(names) <= idx:
            return None
        return self._lookup(names[idx])

    def inputs(self, slot):
        return [self._lookup(n) for n in self.op.input(slot)]

    def has_input(self, slot):
        return bool(self.op.input(slot))

    def _lookup(self, name):
        if name in self.env:
            return self.env[name]
        raise KeyError(
            "Op %s reads %r which has no runtime value. Did you run the "
            "startup program / feed this variable?" % (self.op, name))

    # outputs ----------------------------------------------------------------
    def set_output(self, slot, value, idx=0):
        names = self.op.output(slot)
        if len(names) <= idx:
            return  # optional output not wired
        if self.value_hook is not None:
            value = self.value_hook(names[idx], value)
        self.env[names[idx]] = value

    def set_outputs(self, slot, values):
        for i, v in enumerate(values):
            self.set_output(slot, v, idx=i)

    def output_names(self, slot):
        return self.op.output(slot)

    # misc -------------------------------------------------------------------
    def attr(self, name, default=None):
        return self.op.attr(name, default)

    def next_rng(self):
        if self.rng is None:
            raise RuntimeError(
                "Op %s requires randomness in a context without an RNG "
                "(e.g. inside a generic vjp replay). Register a custom grad."
                % self.op.type)
        return self.rng.next()

    def var(self, name) -> Optional[ir.Variable]:
        try:
            return self.block.var(name)
        except KeyError:
            return None

    def input_var(self, slot, idx=0):
        names = self.op.input(slot)
        return self.var(names[idx]) if len(names) > idx else None

    def output_var(self, slot, idx=0):
        names = self.op.output(slot)
        return self.var(names[idx]) if len(names) > idx else None

    def sub_block(self, attr_name="sub_block") -> ir.Block:
        blk = self.attr(attr_name)
        if isinstance(blk, int):
            blk = self.block.program.blocks[blk]
        return blk


def trace_ops(block: ir.Block, env: Dict[str, Any], rng: RngSource,
              value_hook=None):
    """Run every op's lowering over ``env`` (symbolic when tracing, concrete
    when eager). This is the whole 'executor hot loop' — at trace time only.
    ``value_hook(name, value)`` intercepts every produced value (used to pin
    sharding constraints on named intermediates, e.g. @GRAD vars)."""
    from .. import profiler as _prof
    timing = _prof.profiler_enabled()
    for op in block.ops:
        opdef = registry.lookup_checked(op.type)
        t0 = time.perf_counter() if timing else 0.0
        try:
            opdef.lower(LowerContext(op, env, rng, block, value_hook))
        except Exception as e:
            _annotate_op_error(e, op)
            raise
        if timing:
            _prof.record_op_event(op.type, op.output_arg_names[0]
                                  if op.output_arg_names else op.type,
                                  t0, time.perf_counter())


def _annotate_op_error(e, op):
    """Attach the failing op's identity to the exception (the layer-aware
    crash context of reference utils/CustomStackTrace.h): deep trace
    errors otherwise point at jax internals with no hint WHICH program op
    produced the offending computation."""
    note = ("while lowering op %r (inputs=%s -> outputs=%s)"
            % (op.type, op.input_arg_names, op.output_arg_names))
    try:
        e.add_note(note)
    except AttributeError:
        # BaseException.add_note is 3.11+; on older interpreters set the
        # PEP 678 __notes__ list by hand — tracebacks and tests read it
        # the same way either version
        try:
            notes = getattr(e, "__notes__", None)
            if isinstance(notes, list):
                notes.append(note)
            else:
                e.__notes__ = [note]
        except Exception:
            pass
    except Exception:
        pass  # non-annotatable exception type; never mask the original


class FunctionalContext(LowerContext):
    """LowerContext over explicit value dicts — used by the generic-vjp grad
    path to replay a forward lowering as a pure function."""

    def __init__(self, op, in_values: Dict[str, List[Any]], attrs: Dict[str, Any],
                 outputs=None, type=None):
        fake = ir.Operator.__new__(ir.Operator)
        fake.block = op.block
        fake.type = type or op.type
        fake.inputs = {s: ["#%s#%d" % (s, i) for i in range(len(v))]
                       for s, v in in_values.items()}
        fake.outputs = dict(outputs if outputs is not None else op.outputs)
        fake.attrs = attrs
        env = {}
        for s, vals in in_values.items():
            for i, v in enumerate(vals):
                env["#%s#%d" % (s, i)] = v
        super(FunctionalContext, self).__init__(fake, env, None, op.block)
        self.collected: Dict[str, List[Any]] = {}

    def set_output(self, slot, value, idx=0):
        self.collected.setdefault(slot, [])
        lst = self.collected[slot]
        while len(lst) <= idx:
            lst.append(None)
        lst[idx] = value


# ---------------------------------------------------------------------------


def _op_sub_blocks(op: ir.Operator):
    """Sub-blocks attached to a control-flow op, whether stored as Block
    objects or as block indices (both forms are accepted by
    LowerContext.sub_block)."""
    for key, a in op.attrs.items():
        if isinstance(a, ir.Block):
            yield a
        elif isinstance(a, int) and key in ("sub_block", "block"):
            yield op.block.program.blocks[a]


class _SegView(object):
    """A block facade exposing only a slice of ops (hybrid segments) while
    delegating var lookups etc. to the real block."""

    __slots__ = ("_block", "ops")

    def __init__(self, block, ops):
        self._block = block
        self.ops = ops

    def __getattr__(self, name):
        return getattr(self._block, name)


class _HybridNotTraceable(Exception):
    """A device op in a hybrid segment read a value jit can't consume."""


_HYBRID_BAILOUT = (jax.errors.ConcretizationTypeError,
                   jax.errors.TracerArrayConversionError,
                   jax.errors.TracerBoolConversionError,
                   jax.errors.TracerIntegerConversionError,
                   _HybridNotTraceable)


def _has_sub_blocks(block: ir.Block) -> bool:
    for op in block.ops:
        for _ in _op_sub_blocks(op):
            return True
    return False


def _op_is_host(opdef, op) -> bool:
    h = opdef.host
    return bool(h(op)) if callable(h) else bool(h)


def _is_host_block(block: ir.Block) -> bool:
    for op in _iter_ops(block):
        opdef = registry.lookup(op.type)
        if opdef is not None and _op_is_host(opdef, op):
            return True
    return False


def _referenced_names(block: ir.Block, acc=None):
    """All var names read/written anywhere in a block (incl. sub-blocks)."""
    acc = set() if acc is None else acc
    for op in block.ops:
        acc.update(op.input_arg_names)
        acc.update(op.output_arg_names)
        for sub in _op_sub_blocks(op):
            _referenced_names(sub, acc)
    return acc


def _feed_signature(feed: Dict[str, Any]):
    sig = []
    for name in sorted(feed):
        v = feed[name]
        if isinstance(v, TracedLoD):
            sig.append((name, tuple(v.data.shape), str(v.data.dtype),
                        tuple(len(l) for l in v.lod), v.max_lens))
        else:
            sig.append((name, tuple(v.shape), str(v.dtype)))
    return tuple(sig)


def _to_device_value(v, device=None):
    """Normalise a fed python value into a jnp array or TracedLoD."""
    if isinstance(v, LoDTensor):
        data = jax.device_put(np.asarray(v.numpy()), device)
        host_lod = v.lod()
        lod = tuple(jax.device_put(np.asarray(l, dtype=np.int32), device)
                    for l in host_lod)
        if lod:
            max_lens = tuple(
                int(max((b - a for a, b in zip(l, l[1:])), default=0))
                for l in host_lod)
            return TracedLoD(data, lod, max_lens=max_lens)
        return data
    if isinstance(v, TracedLoD):
        return v
    if isinstance(v, jax.Array):
        # already device-resident (prepare_feed / previous fetch): device_put
        # of a committed array is a no-op. Round-tripping through np.asarray
        # here would force a device->host transfer per step — catastrophic
        # over a tunneled TPU (10s/step class, not microseconds).
        return jax.device_put(v, device) if device is not None else v
    return jax.device_put(np.asarray(v), device)


# scalar fetches on the explicit-comm path are pmean'd back to their
# global meaning — sound ONLY for mean-type batch reductions (possibly
# through linear ops): a reduce_sum fetch would come back divided by
# the axis size, a max fetch as a mean of per-shard maxima
_MEAN_SCALAR_OPS = frozenset(("mean", "accuracy"))
_LINEAR_SCALAR_OPS = frozenset(("scale", "cast", "assign", "sum",
                                "elementwise_add", "elementwise_sub"))


def _scalar_fetch_sound(ops, name, persistables, feeds, depth=8):
    """True when pmean-ing the per-shard scalar ``name`` recovers its
    global-batch meaning: it must resolve, through linear ops only, to
    mean-type reductions or replicated (producer-less non-feed) state.
    Unknown producers fail closed — the build falls back to GSPMD."""
    if depth <= 0:
        return False
    producer = None
    for op_ in ops:
        if name in op_.output_arg_names:
            producer = op_  # last write wins
    if producer is None:
        # state/persistable scalars are replicated -> pmean is identity;
        # a raw feed (batch-shaped) reaching here means we lost track
        return name in persistables and name not in feeds
    if producer.type in _MEAN_SCALAR_OPS:
        return True
    if producer.type in _LINEAR_SCALAR_OPS:
        return all(_scalar_fetch_sound(ops, i, persistables, feeds,
                                       depth - 1)
                   for i in producer.input_arg_names)
    return False


def _comm_flags_sig():
    """Comm-flag fingerprint for the jit caches: the compiled step under
    a mesh embeds the comm policy (explicit collective routing and/or
    the recorded byte model), so a policy flip must recompile."""
    from ..flags import FLAGS
    return (FLAGS.comm_policy, FLAGS.comm_quant, FLAGS.comm_bucket_mb,
            FLAGS.comm_hosts, FLAGS.comm_split_ratio, FLAGS.comm_overlap,
            FLAGS.comm_gspmd)


def _verify_requested():
    """True when the opt-in static verifier is on (PADDLE_TPU_VERIFY=1
    env or FLAGS.verify) — shared by the pre-trace program verify and
    the explicit-comm path's collective-consistency pass."""
    import os
    if os.environ.get("PADDLE_TPU_VERIFY", "").lower() in (
            "1", "true", "yes", "on"):
        return True
    from ..flags import FLAGS
    return bool(FLAGS.verify)


def _dist_shardings(dist, state, feed):
    """in_shardings pytree for ``fn(state, feed, rng_key)`` under a mesh.

    Params/persistables follow the DistContext's spec map; feeds shard their
    batch (leading) dim over the data axis when divisible, else replicate;
    LoD offset arrays are global (replicated) alongside batch-sharded data;
    the RNG key replicates. This is the whole 'distribute transpile' at the
    execution layer — XLA GSPMD derives every collective from these seeds
    (replaces reference: distribute_transpiler.py:132 program rewriting).
    """
    from jax.sharding import NamedSharding
    mesh = dist.mesh
    repl = dist.replicated()

    def feed_shard(name, v):
        if isinstance(v, TracedLoD):
            # LoD offsets are global: replicate alongside batch-sharded data
            return TracedLoD(feed_shard(name, v.data), (repl,) * len(v.lod),
                             max_lens=v.max_lens)
        spec = dist.strategy.spec_for_feed(name, getattr(v, "shape", ()), mesh)
        return NamedSharding(mesh, spec)

    state_sh = {n: dist.sharding_for(n, v) for n, v in state.items()}
    feed_sh = {n: feed_shard(n, v) for n, v in feed.items()}
    return (state_sh, feed_sh, repl)


class AsyncFetch(object):
    """Lazy fetch handle (``Executor.run(..., sync=False)``).

    Wraps a still-on-device value instead of round-tripping it through
    ``block_until_ready`` + numpy on every step — the fetch half of the
    async execution pipeline (see paddle_tpu/pipeline.py). The device
    value materialises to host exactly once, at first access:

    - ``value()`` / ``numpy()`` / ``float(h)`` / ``np.asarray(h)``
    - ``block()`` waits for the device computation WITHOUT transferring
    - ``ready`` polls completion without blocking

    Materialisation is counted in the owning Executor's
    ``stats["fetch_sync_count"]`` so the pipeline's sync points stay
    observable.
    """

    __slots__ = ("_value", "_host", "_done", "_return_numpy", "_stats")

    def __init__(self, value, return_numpy=True, stats=None):
        self._value = value
        self._return_numpy = return_numpy
        self._host = None
        self._done = False
        self._stats = stats

    @property
    def ready(self):
        """True once the device computation behind this value finished
        (a materialised handle is trivially ready)."""
        if self._done:
            return True
        try:
            return all(l.is_ready() for l
                       in jax.tree_util.tree_leaves(self._value)
                       if hasattr(l, "is_ready"))
        except Exception:
            return True

    def block(self):
        """Wait for the device value without fetching it to host."""
        try:
            jax.block_until_ready(self._value)
        except Exception:
            pass  # host-side values (eager path) have nothing to wait on
        return self

    def value(self):
        """Materialise (once) and return the host value."""
        if not self._done:
            self._host = _fetch_to_host(self._value, self._return_numpy)
            self._done = True
            self._value = None  # release the device buffer reference
            if self._stats is not None:
                self._stats["fetch_sync_count"] += 1
            from .. import profiler as _prof
            _prof.update_pipeline_counters(fetch_sync_count=1)
        return self._host

    def numpy(self):
        return np.asarray(self.value())

    def __array__(self, dtype=None):
        a = np.asarray(self.value())
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(np.asarray(self.value()).reshape(-1)[0])

    def __repr__(self):
        state = ("materialized" if self._done
                 else "ready" if self.ready else "pending")
        return "AsyncFetch(%s)" % state


def _fetch_to_host(val, return_numpy=True):
    if isinstance(val, ConcreteScalar):
        val = val.data
    if isinstance(val, TracedLoD):
        t = LoDTensor(np.asarray(val.data),
                      [list(np.asarray(l)) for l in val.lod])
        return t
    from ..ops.selected_rows import SelectedRowsVal
    if isinstance(val, SelectedRowsVal):
        # keep the row structure (np.asarray would produce a useless 0-d
        # object array); callers that want dense use .to_dense()
        return SelectedRowsVal(np.asarray(val.rows),
                               np.asarray(val.values), val.height)
    if return_numpy:
        return np.asarray(val)
    return val


# Process-level warm-start compile registry: compiled step functions keyed
# exactly like the per-Executor cache, shared across Executor instances so a
# second Executor over the same (program uid, version, feed signature) skips
# the trace+compile entirely (the in-process half of the persistent compile
# cache; the cross-process half is jax's compilation_cache_dir, configured by
# paddle_tpu.pipeline.maybe_enable_compile_cache). Bounded: cleared wholesale
# past _WARM_JIT_LIMIT entries (keys embed program uids, which are never
# reused in-process, so stale entries are dead weight, not corruption).
_WARM_JIT_CACHE: Dict[Any, Any] = {}
_WARM_JIT_LIMIT = 256

# One process-wide lock for FIRST calls of compiled step functions.
# jax.jit is lazy: _compile returns untraced wrappers, and the trace that
# runs on the first call walks the SHARED Program/Variable objects,
# annotating shapes/dtypes as it goes. Two executors first-calling
# concurrently (the async-SGD worker pattern: N threads, one program)
# interleave those mutations and one thread bakes a numerically WRONG
# trace into its per-executor cache — every later run of that executor is
# silently corrupt (reproduced: 3 worker threads on the forced-8-device
# CPU mesh diverged to nan; bit-exact once first calls serialize).
# Serialized first calls cost nothing steady-state: the traced fn is
# marked ready and later calls take jax's lock-free C++ fast path.
_FIRST_TRACE_LOCK = threading.Lock()

# Per-program locks for the eager/hybrid paths. The jit path only walks
# the shared Program/Variable objects on its FIRST call (serialized by
# _FIRST_TRACE_LOCK above) — but the per-op interpreter and the hybrid
# segment runner re-trace the shared program state on EVERY run (op attr
# setdefaults, variable shape/dtype annotation, ConcreteScalar counter
# propagation). Two executors eager-running one program concurrently
# interleave those mutations exactly like the first-trace race PR 5
# fixed for jit. One RLock per program uid: same-program eager runs
# serialize (that is the correctness requirement), different programs
# stay concurrent; re-entrant because a hybrid bailout re-enters
# trace_ops on the same thread.
_EAGER_LOCKS_GUARD = threading.Lock()
_EAGER_TRACE_LOCKS: Dict[int, "threading.RLock"] = {}


def _program_trace_lock(uid):
    with _EAGER_LOCKS_GUARD:
        lk = _EAGER_TRACE_LOCKS.get(uid)
        if lk is None:
            if len(_EAGER_TRACE_LOCKS) > 1024:
                # bound dead-program locks — but evict only UNHELD ones:
                # dropping a lock another thread is inside would hand a
                # fresh lock to the next caller and reintroduce the
                # shared-program trace race this registry exists to stop
                for dead_uid in list(_EAGER_TRACE_LOCKS):
                    dead = _EAGER_TRACE_LOCKS[dead_uid]
                    if dead.acquire(blocking=False):
                        dead.release()
                        del _EAGER_TRACE_LOCKS[dead_uid]
            lk = _EAGER_TRACE_LOCKS[uid] = threading.RLock()
        return lk


class _TracedOnce(object):
    """Compiled-step wrapper that serializes the tracing first call."""

    __slots__ = ("fn", "_ready")

    def __init__(self, fn):
        self.fn = fn
        self._ready = threading.Event()

    def __call__(self, *args):
        if self._ready.is_set():
            return self.fn(*args)
        with _FIRST_TRACE_LOCK:
            out = self.fn(*args)
        self._ready.set()
        return out


def clear_warm_cache():
    """Drop the process-level compiled-step registry (test isolation)."""
    _WARM_JIT_CACHE.clear()


class Executor(object):
    """reference: python/paddle/fluid/executor.py:166 (class Executor) /
    paddle/fluid/framework/executor.cc:86 (Executor::Run)."""

    def __init__(self, place=None, dist_context=None, check_nan_inf=None):
        from .. import place as place_mod
        self.place = place if place is not None else place_mod.TPUPlace()
        self._cache: Dict[Any, Any] = {}
        self._device_cache = None
        # DistContext from paddle_tpu.parallel: when set, the jitted block is
        # compiled with mesh shardings (SPMD) instead of pinned to one device
        self.dist_context = dist_context
        # FLAGS_check_nan_inf analog; forces the eager path when on.
        # None defers to the process flag at each run(), like the reference
        # reading FLAGS inside Run() (reference: executor.cc:30) — so a
        # flags_guard around run() takes effect on an existing Executor
        self._check_nan_inf_arg = check_nan_inf
        # which path each run() took — tests assert dynamic-control-flow
        # programs really compile (VERDICT r1 item 3); hybrid = host ops
        # interpreted between jitted device segments. The pipeline counters
        # (lazy_fetches/fetch_sync_count/compile_cache_hits/feed_wait_ms/
        # dispatch_depth) make the async execution pipeline observable:
        # overlap is only real when feed_wait stays below step time and
        # fetch syncs stay rare (see doc/async_pipeline.md)
        # the comm_* entries model the DP grad-sync wire traffic of the
        # compiled program under the active comm policy (paddle_tpu.comm;
        # refreshed per compile), and record quant fallbacks folded in by
        # comm.record_step_stats(..., stats=exe.stats)
        # the tune_* entries mirror paddle_tpu.tune's process-level
        # kernel-dispatch counters (hits = cached winner applied, misses
        # = kernel default config, fallbacks = stock XLA); dispatch
        # happens at trace time, so they move once per compile — the
        # snapshot refreshes at the end of every run()
        # comm_path says HOW the last compiled program's DP grads sync:
        # "explicit" = routed through the paddle_tpu.comm collectives
        # (comm_* stats measured from the traced plan), "model" = GSPMD
        # owns the schedule and comm_* is the byte model, "" = no DP
        # sync compiled yet
        # the elastic_* entries mirror paddle_tpu.elastic's process-level
        # counters (world resizes, lost ranks, requeued dataset tasks,
        # cross-world resume latency) folded in by
        # elastic.record_stats(stats=exe.stats)
        self.stats = {"jit_runs": 0, "eager_runs": 0, "hybrid_runs": 0,
                      "lazy_fetches": 0, "fetch_sync_count": 0,
                      "compile_cache_hits": 0, "feed_wait_ms": 0.0,
                      "dispatch_depth": 0, "comm_bytes": 0,
                      "comm_buckets": 0, "comm_quant_fallbacks": 0,
                      "comm_path": "",
                      "tune_hits": 0, "tune_misses": 0,
                      "tune_fallbacks": 0,
                      "elastic_resizes": 0, "elastic_lost_ranks": 0,
                      "elastic_requeued_tasks": 0,
                      "elastic_resume_ms": 0.0,
                      # the memory preflight's last predicted peak
                      # (PADDLE_TPU_VERIFY; analysis.memory PT030)
                      "mem_predicted_peak_bytes": 0}
        # programs whose trace hit data-dependent control flow: run eager
        self._force_eager = set()
        # (uid, version) pairs already checked by the pre-trace verifier
        # (PADDLE_TPU_VERIFY / FLAGS.verify): verify once per program
        # version, not per step
        self._verified = set()
        # programs already warned about host-path degradation (one line per
        # program, not per step)
        self._degradation_logged = set()
        # scope (weak) -> {(names-version, program uid/version, feeds) ->
        # (state_names, state signature)}: avoids rebuilding the sorted
        # O(n_params) signature tuple every step (VERDICT r1 weak 11).
        # Weak keying prevents unbounded growth and id-reuse staleness
        # across scope lifetimes.
        import weakref
        self._state_memo = weakref.WeakKeyDictionary()

    @property
    def check_nan_inf(self):
        if self._check_nan_inf_arg is not None:
            return self._check_nan_inf_arg
        from ..flags import FLAGS
        return FLAGS.check_nan_inf

    @check_nan_inf.setter
    def check_nan_inf(self, v):
        self._check_nan_inf_arg = v

    def _device(self):
        """Resolve the jax device this Place pins; None = jax default."""
        if self._device_cache is None:
            try:
                devs = jax.devices(self.place.backend)
                idx = getattr(self.place, "device_id", 0)
                self._device_cache = devs[min(idx, len(devs) - 1)]
            except RuntimeError:
                # backend unavailable (e.g. TPUPlace on a CPU-only host):
                # fall back to the default backend rather than failing
                self._device_cache = jax.devices()[0]
        return self._device_cache

    # -- public API ----------------------------------------------------------
    def prepare_feed(self, feed, local_shard=False):
        """Transfer a feed dict to the device once; the returned dict can be
        passed to run() repeatedly without re-transferring (device_put of an
        already-committed array is a no-op). The reference's analog is the
        data-provider double buffer keeping batches device-resident.

        ``local_shard=True`` (multi-host, needs a dist_context): each
        process passes only ITS slice of the global batch — the slices are
        assembled into one global array sharded per the strategy's feed
        spec (``jax.make_array_from_process_local_data``). This is the
        reference's per-trainer data shard (each trainer reads its own
        file split / master leases) in SPMD form."""
        if local_shard:
            dist = self.dist_context
            if dist is None:
                raise ValueError("local_shard feeds need a dist_context")
            out = {}
            nproc = jax.process_count()
            for k, v in feed.items():
                if isinstance(v, LoDTensor):
                    raise NotImplementedError(
                        "local_shard feeds don't carry LoD yet — feed "
                        "ragged data replicated (plain prepare_feed) or "
                        "pre-pad to dense")
                arr = np.asarray(v)
                # the sharding decision must see the GLOBAL batch shape
                # (divisibility checks against a local slice would flip
                # small feeds to replicated)
                gshape = ((arr.shape[0] * nproc,) + tuple(arr.shape[1:])
                          if arr.ndim else arr.shape)
                spec = dist.strategy.spec_for_feed(k, gshape, dist.mesh)
                if not any(p is not None for p in tuple(spec)):
                    # a replicated spec + per-rank local slices would
                    # install DIFFERENT buffers as "the" replicated array:
                    # silent cross-host divergence. Refuse loudly.
                    raise ValueError(
                        "local_shard feed %r resolves to a replicated "
                        "spec (global batch %s not divisible by the data "
                        "axis?) — pass identical data via plain "
                        "prepare_feed instead" % (k, gshape))
                sh = jax.sharding.NamedSharding(dist.mesh, spec)
                out[k] = jax.make_array_from_process_local_data(sh, arr)
            return out
        dev = None if self.dist_context is not None else self._device()
        return {k: _to_device_value(v, dev) for k, v in feed.items()}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_jit=True, feed_var_name="feed",
            fetch_var_name="fetch", dist_context=None, repeat=1,
            sync=True):
        """``repeat=K`` compiles K whole training steps into one
        ``lax.scan`` dispatch (fetches come from the last step). This is the
        standard TPU step-fusion pattern: one host round-trip amortises K
        steps of dispatch and argument shipping — the modern analog of the
        reference's num_batches_per_send_parameter local accumulation
        (reference: utils/Flags.cpp:44-65). Requires the jit path and a
        constant feed across the K steps.

        ``sync=False`` returns :class:`AsyncFetch` handles backed by the
        still-on-device fetch values instead of blocking on a device->host
        transfer per call — the dispatch stays asynchronous and the host
        is free to prepare the next feed while the device computes (the
        fetch half of paddle_tpu.pipeline). Values materialise lazily at
        first access; paths that compute eagerly on the host
        (``check_nan_inf``, host ops) still return handles, just trivially
        ready ones."""
        program = program if program is not None else ir.default_main_program()
        self._maybe_verify(program)
        scope = scope if scope is not None else global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [f.name if isinstance(f, ir.Variable) else f
                       for f in fetch_list]
        dist = dist_context if dist_context is not None else self.dist_context

        # under a mesh, leave feeds uncommitted: jit's in_shardings place them
        dev = None if dist is not None else self._device()
        dev_feed = {k: _to_device_value(v, dev) for k, v in feed.items()}
        block = program.global_block()

        from .. import profiler as _prof
        timing = _prof.profiler_enabled()
        t0 = time.perf_counter() if timing else 0.0
        if (_is_host_block(block) or not use_jit or self.check_nan_inf
                or program._uid in self._force_eager):
            # host ops (save/load) can't be jit-traced. Instead of dropping
            # the WHOLE program to the per-op interpreter (r1 weak item 3),
            # partition it: contiguous device-op segments jit-compile,
            # host ops interpret between them (the reference pays per-op
            # dispatch everywhere; here only the host ops do).
            if repeat != 1:
                raise ValueError("repeat>1 requires the jit path")
            hybrid_ok = (use_jit and not self.check_nan_inf
                         and dist is None
                         and program._uid not in self._force_eager
                         and not _has_sub_blocks(block))
            if (use_jit and _is_host_block(block)
                    and program._uid not in self._degradation_logged):
                # one-line diagnostic so a user training e.g. SSD knows
                # their graph partially (or fully) runs eagerly
                # (VERDICT r3 weak 7)
                self._degradation_logged.add(program._uid)
                from collections import Counter
                host = Counter(
                    op.type for op in _iter_ops(block)
                    if (registry.lookup(op.type) is not None
                        and _op_is_host(registry.lookup(op.type), op)))
                n_ops = sum(1 for _ in _iter_ops(block))
                _LOG.warning(
                    "program %d contains %d host-path op(s) of %d total"
                    " (%s): %s",
                    program._uid, sum(host.values()), n_ops,
                    ", ".join("%s x%d" % kv for kv in sorted(host.items())),
                    "device segments still jit, but these ops interpret "
                    "on the host each step" if hybrid_ok else
                    "the whole program runs on the per-op interpreter "
                    "path (sub-blocks or flags prevent hybrid "
                    "segmentation)")
            if hybrid_ok:
                # bailouts are handled INSIDE _run_hybrid (it finishes the
                # current run eagerly from the failure point, so host side
                # effects that already ran are not repeated)
                outs = self._run_hybrid(program, dev_feed, fetch_names,
                                        scope)
                self.stats["hybrid_runs"] += 1
            else:
                self.stats["eager_runs"] += 1
                outs = self._run_eager(program, dev_feed, fetch_names,
                                       scope)
        else:
            try:
                outs = self._run_jit(program, dev_feed, fetch_names, scope,
                                     dist=dist, repeat=repeat)
                self.stats["jit_runs"] += 1
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.TracerBoolConversionError,
                    jax.errors.TracerIntegerConversionError) as e:
                # genuinely data-dependent control flow (a While condition /
                # array index computed from fed data, not a ConcreteScalar
                # counter chain): tracing can't unroll it. Fall back to the
                # reference's per-op interpreter semantics for this program.
                if repeat != 1:
                    raise
                import warnings
                warnings.warn(
                    "program %d hit data-dependent control flow during jit "
                    "tracing and will run on the per-op interpreter path "
                    "from now on (10-100x slower on TPU). Cause: %s"
                    % (program._uid, str(e).splitlines()[0]), RuntimeWarning)
                self._force_eager.add(program._uid)
                self.stats["eager_runs"] += 1
                outs = self._run_eager(program, dev_feed, fetch_names, scope)
        if timing:
            jax.block_until_ready([raw_data(o) for o in outs])
            _prof.record_run("program_%d_run" % program._uid,
                             time.perf_counter() - t0)
        from .. import tune as _tune
        self.stats.update(_tune.counters())
        if not sync:
            self.stats["lazy_fetches"] += len(outs)
            return [AsyncFetch(o, return_numpy=return_numpy,
                               stats=self.stats) for o in outs]
        return [_fetch_to_host(o, return_numpy) for o in outs]

    # -- hybrid path: jitted device segments + interpreted host ops ----------
    def _run_hybrid(self, program, feed, fetch_names, scope):
        """Programs containing host ops (save/print/NMS/bipartite_match…)
        run as: [jit segment] [host op] [jit segment] … — the device math
        compiles, only the genuinely host-bound ops interpret. The
        reference interprets EVERY op (executor.cc:125); round 1 here
        dropped such programs entirely to the interpreter (weak item 3).

        Serialized per program: unlike the jit path (one mutating trace,
        then pure compiled calls), this path re-walks the shared Program
        state every run — see _program_trace_lock."""
        with _program_trace_lock(program._uid):
            return self._run_hybrid_impl(program, feed, fetch_names, scope)

    def _run_hybrid_impl(self, program, feed, fetch_names, scope):
        from .. import profiler as _prof
        _prof.set_phase("eager")
        block = program.global_block()
        env = dict(feed)
        state_names = self._state_inputs(program, scope, feed)
        for n in state_names:
            env[n] = scope.find_var(n)
        env["@SCOPE@"] = scope

        # static per-program analysis, memoized on (uid, version, fetches):
        # rebuilding the partition + reverse-liveness chain every step would
        # be O(#ops) Python work per run (cf. the _state_memo rationale)
        akey = (program._uid, program._version, "hyb-analysis",
                tuple(fetch_names), tuple(state_names))
        cached = self._cache.get(akey)
        if cached is None:
            segments = self._partition_segments(block)
            persist = self._persistable_names(program)
            keep = set(fetch_names) | persist | set(state_names)
            later_reads = []
            acc = set(keep)
            for kind, ops in reversed(segments):
                later_reads.append(set(acc))
                for op in ops:
                    acc.update(op.input_arg_names)
            later_reads.reverse()
            self._cache[akey] = (segments, later_reads)
        else:
            segments, later_reads = cached

        rng_key = self._rng_key(program, scope)
        for idx, (kind, ops) in enumerate(segments):
            if kind == "host":
                rng = RngSource(rng_key)
                trace_ops(_SegView(block, ops), env, rng)
                rng_key = rng.key
                continue
            try:
                rng_key = self._run_segment_jit(program, block, ops, idx,
                                                env, later_reads[idx],
                                                rng_key)
            except _HYBRID_BAILOUT as e:
                # finish THIS run eagerly from the failure point — host
                # side effects of earlier segments must not repeat — and
                # downgrade the program permanently (loudly, like the jit
                # path's interpreter warning)
                import warnings
                warnings.warn(
                    "program %d left the hybrid path (%s) and will run on "
                    "the per-op interpreter from now on (10-100x slower "
                    "on TPU)" % (program._uid, str(e).splitlines()[0]),
                    RuntimeWarning)
                self._force_eager.add(program._uid)
                rest = [op for _, seg in segments[idx:] for op in seg]
                rng = RngSource(rng_key)
                trace_ops(_SegView(block, rest), env, rng)
                rng_key = rng.key
                break
        self._writeback(program, scope, env, rng_key)
        return [env[n] for n in fetch_names]

    def _run_segment_jit(self, program, block, ops, idx, env, keep_after,
                         rng_key):
        reads = []
        for op in ops:
            for n in op.input_arg_names:
                if n in env and n not in reads:
                    reads.append(n)
        writes = {n for op in ops for n in op.output_arg_names}
        out_names = tuple(sorted(writes & keep_after))
        arr_in, static_in, sig = {}, {}, []
        for n in reads:
            v = env[n]
            if isinstance(v, (TracedLoD, jax.Array, np.ndarray)):
                arr_in[n] = v
                if isinstance(v, TracedLoD):
                    sig.append((n, "lod", tuple(v.data.shape),
                                str(v.data.dtype), len(v.lod),
                                v.max_lens))
                else:
                    sig.append((n, tuple(v.shape), str(v.dtype)))
            elif isinstance(v, ConcreteScalar):
                static_in[n] = v
                sig.append((n, "concrete", v.value))
            elif isinstance(v, (bool, int, float, str, bytes,
                                type(None))):
                static_in[n] = v
                sig.append((n, "static", v))
            else:
                # non-traceable value (channel, tensor array…) read by a
                # device op: this program can't hybridize
                raise _HybridNotTraceable(
                    "device op reads non-traceable %r (%s)"
                    % (n, type(v).__name__))
        key = (program._uid, program._version, "hyb", idx,
               tuple(sig), out_names)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._compile_segment(block, ops, out_names,
                                       dict(static_in))
            self._cache[key] = fn
        try:
            outs, rng_key = fn(arr_in, rng_key)
        except Exception:
            self._cache.pop(key, None)
            raise
        env.update(outs)
        return rng_key

    def _partition_segments(self, block):
        segs = []
        for op in block.ops:
            opdef = registry.lookup_checked(op.type)
            kind = "host" if _op_is_host(opdef, op) else "dev"
            if segs and segs[-1][0] == kind:
                segs[-1][1].append(op)
            else:
                segs.append((kind, [op]))
        return segs

    def _compile_segment(self, block, ops, out_names, static_in):
        # ConcreteScalar outputs ride through jit intact: the class is a
        # registered pytree whose python value is aux data, so downstream
        # segments with counter-indexed array ops stay hybrid. (The value
        # is a pure function of the static cache-keyed inputs, so the
        # trace-time value is correct for every call of this compilation.)
        def seg_fn(inputs, rng_key):
            env = dict(static_in)
            env.update(inputs)
            rng = RngSource(rng_key)
            trace_ops(_SegView(block, ops), env, rng)
            return {n: env[n] for n in out_names}, rng.key

        return jax.jit(seg_fn)

    # -- eager path (host ops, debugging) -------------------------------------
    def _run_eager(self, program, feed, fetch_names, scope):
        """Per-op interpreter run, serialized per program: trace_ops
        annotates the SHARED Program/Variable objects as it walks (the
        jit path does this once under _FIRST_TRACE_LOCK; here it happens
        every run), so concurrent eager executors over one program must
        take turns — see _program_trace_lock."""
        with _program_trace_lock(program._uid):
            return self._run_eager_impl(program, feed, fetch_names, scope)

    def _run_eager_impl(self, program, feed, fetch_names, scope):
        from .. import profiler as _prof
        _prof.set_phase("eager")
        block = program.global_block()
        env = dict(feed)
        state_names = self._state_inputs(program, scope, feed)
        for n in state_names:
            env[n] = scope.find_var(n)
        rng = RngSource(self._rng_key(program, scope))
        env["@SCOPE@"] = scope  # host ops (save/load) reach the scope directly
        value_hook = None
        if self.check_nan_inf:
            # FLAGS_check_nan_inf analog (reference: executor.cc:30,135-143
            # per-op output scan) — eager-path debug guard
            def value_hook(name, value):
                data = raw_data(value)
                if hasattr(data, "dtype") and jnp.issubdtype(
                        jnp.asarray(data).dtype, jnp.floating):
                    if not bool(jnp.isfinite(data).all()):
                        raise FloatingPointError(
                            "NaN/Inf detected in %r" % name)
                return value
        trace_ops(block, env, rng, value_hook)
        self._writeback(program, scope, env, rng.key)
        return [env[n] for n in fetch_names]

    # -- jit path --------------------------------------------------------------
    def _run_jit(self, program, feed, fetch_names, scope, dist=None,
                 repeat=1):
        per_scope = self._state_memo.setdefault(scope, {})
        # parent scopes can own persistables found via the lookup walk;
        # include their name-set versions so additions there invalidate
        vers = []
        sc = scope
        while sc is not None:
            vers.append(sc._names_version)
            sc = sc.parent
        memo_key = (tuple(vers), program._uid, program._version,
                    tuple(sorted(feed)))
        cached = per_scope.get(memo_key)
        if cached is None:
            state_names = self._state_inputs(program, scope, feed)
            state = {n: scope.find_var(n) for n in state_names}
            state_sig = tuple(sorted(
                (n, tuple(getattr(v, "shape", ())),
                 str(getattr(v, "dtype", type(v).__name__)))
                for n, v in state.items()))
            if len(per_scope) > 32:  # bound stale-version entries
                per_scope.clear()
            per_scope[memo_key] = (state_names, state_sig)
        else:
            state_names, state_sig = cached
            state = {n: scope.find_var(n) for n in state_names}
        # numpy-valued state (a fresh pserver pull, a user set_var) must be
        # COPIED into an XLA-owned device buffer before the donated call:
        # jax zero-copy-aliases aligned host buffers, so donating one hands
        # XLA memory whose python owner can be dropped (scope replaces the
        # entry with new_state right after the async dispatch) and recycled
        # mid-execution. Under concurrent executors that read/write recycle
        # produced silently WRONG gradients — reproduced deterministically
        # by tests/test_async_sgd.py's 3-worker pattern on the forced
        # 8-device CPU mesh; bit-exact with the copy. Device-array state
        # (the steady training loop) passes through untouched.
        raw_state = state
        state = {n: jnp.array(v) if isinstance(v, np.ndarray) else v
                 for n, v in raw_state.items()}
        # donation-aliasing guard (always-on at this previously-fixed
        # site): nothing numpy-backed may reach the donated argument
        # position; PADDLE_TPU_SANITIZE=alias additionally proves the
        # copies above did not zero-copy alias their host sources
        from ..analysis.sanitize import check_donated
        check_donated(state, "executor._run_jit", always=True,
                      host_sources={n: v for n, v in raw_state.items()
                                    if isinstance(v, np.ndarray)})
        if dist is not None:
            # align committed buffers with the declared shardings (no-op when
            # already placed; reshards e.g. replicated startup output → tp)
            state = {n: jax.device_put(v, dist.sharding_for(n, v))
                     for n, v in state.items()}
        from .. import profiler as _prof
        key = (program._uid, program._version, _feed_signature(feed),
               tuple(fetch_names), repeat, _prof.profiler_enabled(),
               dist.cache_token() if dist is not None else None,
               # the compiled step depends on the comm flags under a
               # mesh (explicit collective routing + the byte model):
               # a flags_guard flip must not hit a stale compile
               _comm_flags_sig() if dist is not None else None,
               state_sig)
        fn = self._cache.get(key)
        if fn is None:
            # warm start: another Executor in this process already compiled
            # this exact (program, feed signature, fetches, state) step
            fn = _WARM_JIT_CACHE.get(key)
            if fn is not None:
                self._cache[key] = fn
                self.stats["compile_cache_hits"] += 1
                _prof.update_pipeline_counters(compile_cache_hits=1)
        if fn is None:
            # static memory preflight (PADDLE_TPU_VERIFY, PT030): a
            # program whose predicted peak HBM cannot fit the budget
            # raises ONE readable ProgramVerifyError with the residency
            # table HERE — before the XLA compile burns minutes on a
            # step that would only die in an unreadable device OOM.
            # Fresh-compile path only: a cached fn already proved it
            # compiles, and the plan is a function of (program, feed
            # signature, state signature) — exactly this cache key
            if _verify_requested():
                self._memory_preflight(program, feed, state, fetch_names,
                                       dist)
                self._sharding_preflight(program, dist)
            shardings = (_dist_shardings(dist, state, feed)
                         if dist is not None else None)
            fn = _TracedOnce(self._compile(
                program, feed, fetch_names, state_names,
                shardings=shardings, dist=dist, repeat=repeat))
            if dist is not None:
                self._record_comm_model(program, dist)
            self._cache[key] = fn
            if len(_WARM_JIT_CACHE) >= _WARM_JIT_LIMIT:
                _WARM_JIT_CACHE.clear()
            _WARM_JIT_CACHE[key] = fn
        rng_key = self._rng_key(program, scope)
        try:
            fetches, new_state, new_key = fn(state, feed, rng_key)
        except Exception:
            # a failed first trace must not leave a dead compiled fn cached
            self._cache.pop(key, None)
            _WARM_JIT_CACHE.pop(key, None)
            raise
        for n, v in new_state.items():
            scope.set_var(n, v)
        scope.set_var(RNG_VAR, new_key)
        return fetches

    def _explicit_comm_plan(self, program, block, dist, feed_template):
        """Host-side eligibility for routing this program's DP gradient
        sync through the explicit paddle_tpu.comm collectives (instead
        of leaving the schedule to GSPMD and only modelling the bytes).

        Eligible = pure data parallelism with a clean backward/optimizer
        boundary: every persistable replicated, every array feed batch-
        sharded over the data axis, all ``@GRAD`` writes before the
        first update op, and no op whose semantics couple the global
        batch or draw randomness (those change meaning under a
        per-shard trace). Returns a plan dict or ``None`` — ineligible
        programs keep the GSPMD path with the byte model, which is
        always correct."""
        from ..flags import FLAGS
        from .. import comm
        if not FLAGS.comm_gspmd:
            return None
        data_axis = dist.strategy.data_axis
        n = dict(dist.mesh.shape).get(data_axis, 1)
        if n <= 1:
            return None
        try:
            policy = comm.resolve_policy(axis_size=n)
        except Exception:
            return None
        if policy.is_noop:
            # the none policy keeps the pre-explicit GSPMD build
            # bit-identical — the parity contract doc/comm.md states
            return None
        if any(a for spec in dist.specs.values()
               for a in (spec or ()) if a is not None):
            return None  # tensor/ZeRO-sharded vars: not a pure-DP program
        risky = ("dropout", "random", "batch_norm", "lookup_table")
        for op_ in _iter_ops(block):
            if any(r in op_.type for r in risky):
                return None
        param_names = {p.name for p in program.all_parameters()}
        grad_names = {p + ir.GRAD_SUFFIX for p in param_names}
        grad_writes = [i for i, op_ in enumerate(block.ops)
                       if set(op_.output_arg_names) & grad_names]
        update_idx = [i for i, op_ in enumerate(block.ops)
                      if (set(op_.input_arg_names) & grad_names)
                      and (set(op_.output_arg_names) & param_names)]
        if not grad_writes or not update_idx:
            return None  # not a training program (e.g. startup/eval)
        boundary = min(update_idx)
        if max(grad_writes) >= boundary:
            return None  # interleaved backward/update: no clean sync point
        local_batches = set()
        for name, v in feed_template.items():
            if isinstance(v, TracedLoD):
                return None  # LoD offsets are global; per-shard is wrong
            shape = tuple(getattr(v, "shape", ()) or ())
            if not shape:
                continue  # scalar feed replicates harmlessly
            spec = dist.strategy.spec_for_feed(name, shape, dist.mesh)
            if not tuple(spec) or tuple(spec)[0] != data_axis or \
                    shape[0] % n:
                return None  # a replicated array feed would double-count
            local_batches.add(shape[0] // n)
        if not local_batches:
            return None
        stateless = comm.stateless_policy(policy)
        if stateless is not policy:
            import warnings
            warnings.warn(
                "comm_quant=%s carries error-feedback state the Executor "
                "path does not thread; syncing at full precision "
                "(comm_policy=hierarchical/multipath quantises its "
                "inter-host leg statelessly)" % policy.quant)
        return {"axis_name": data_axis, "n": n, "policy": stateless,
                "pre_ops": list(block.ops[:boundary]),
                "post_ops": list(block.ops[boundary:]),
                "grad_names": sorted(grad_names),
                "local_batches": local_batches}

    def _compile_explicit_comm(self, program, block, dist, plan,
                               feed_template, fetch_names, state_names,
                               extra_out, shardings, repeat, fallback):
        """Build the explicit-comm step: the program traces per-device
        under shard_map, ``comm.all_reduce_grads`` carries the DP sync
        at the backward/optimizer boundary (backward-order bucket issue
        when ``FLAGS.comm_overlap``), and scalar fetches pmean back to
        their global meaning. The returned dispatcher decides at FIRST
        call (an ``eval_shape`` dry run, no donation at risk): a build
        that cannot hold the contract — a non-scalar non-batch fetch, a
        trace error — degrades to ``fallback`` (the standard GSPMD jit)
        with a recorded ``comm_degraded`` event. A comm-policy routing
        failure must never kill a job GSPMD could run."""
        from .. import comm
        from ..flags import FLAGS
        from jax.sharding import PartitionSpec as P
        axis_name, n, policy = (plan["axis_name"], plan["n"],
                                plan["policy"])
        grad_names = plan["grad_names"]
        local_batches = plan["local_batches"]
        mesh = dist.mesh
        schedule = "backward" if FLAGS.comm_overlap else None
        capture = {}

        def per_device(state, feed, rng_key, sync=True):
            env = dict(feed)
            env.update(state)
            rng = RngSource(rng_key)
            trace_ops(_SegView(block, plan["pre_ops"]), env, rng, None)
            grads = {g: env[g] for g in grad_names
                     if g in env and hasattr(env[g], "ndim")}
            if not grads:
                raise RuntimeError(
                    "no gradient materialised before the sync boundary")
            capture["grads"] = {
                k: jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v))
                for k, v in grads.items()}
            if sync:  # the shape pre-pass runs outside shard_map, where
                # the axis is unbound — the sync changes no shapes
                synced, _ = comm.all_reduce_grads(
                    grads, axis_name, policy, None, schedule=schedule)
                env.update(synced)
            trace_ops(_SegView(block, plan["post_ops"]), env, rng, None)
            new_state = {nm: raw_data(env[nm]) if isinstance(
                env[nm], ConcreteScalar) else env[nm] for nm in state_names}
            for nm in extra_out:
                if nm in env:
                    v = env[nm]
                    new_state[nm] = raw_data(v) if isinstance(
                        v, ConcreteScalar) else v
            fetches = [env[nm] for nm in fetch_names]
            return fetches, new_state, rng.key

        def local_aval(name, v):
            shape = tuple(v.shape)
            if shape and shape[0] % n == 0:
                spec = dist.strategy.spec_for_feed(name, shape, mesh)
                if tuple(spec) and tuple(spec)[0] == axis_name:
                    shape = (shape[0] // n,) + shape[1:]
            return jax.ShapeDtypeStruct(shape, v.dtype)

        def build(state, feed, rng_key):
            # abstract pre-pass on LOCAL avals: learn each output's
            # per-device shape, then pick out_specs — scalars pmean back
            # to the global mean, batch-leading values reassemble over
            # the data axis; anything else has no sound global meaning
            # under a per-shard trace, so the build refuses (-> fallback)
            st_avals = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(jnp.shape(v),
                                               jnp.result_type(v)), state)
            fd_avals = {k: local_aval(k, v) for k, v in feed.items()}
            key_aval = jax.ShapeDtypeStruct(jnp.shape(rng_key),
                                            jnp.result_type(rng_key))
            out_shape = jax.eval_shape(
                functools.partial(per_device, sync=False),
                st_avals, fd_avals, key_aval)
            f_shapes, ns_shapes, _ = out_shape
            all_ops = plan["pre_ops"] + plan["post_ops"]
            persistables = {v.name for v in program.list_vars()
                            if v.persistable}
            pmean_idx, f_specs = set(), []
            for i, f in enumerate(f_shapes):
                if int(np.prod(f.shape or (1,))) == 1:
                    # scalar (and [1]-shaped scalar-like, the mean op's
                    # shape) fetches pmean back to their global-batch
                    # meaning — but only mean-type reductions survive
                    # that (a reduce_sum would come back divided by n)
                    if not _scalar_fetch_sound(all_ops, fetch_names[i],
                                               persistables, set(feed)):
                        raise RuntimeError(
                            "scalar fetch %r does not resolve to a "
                            "mean-type batch reduction: pmean would "
                            "change its meaning" % fetch_names[i])
                    pmean_idx.add(i)
                    f_specs.append(P())
                elif f.shape[0] in local_batches and \
                        fetch_names[i] not in state_names:
                    f_specs.append(P(*((axis_name,)
                                       + (None,) * (len(f.shape) - 1))))
                else:
                    raise RuntimeError(
                        "fetch %r is neither scalar nor batch-leading "
                        "(local shape %r): no sound per-shard assembly"
                        % (fetch_names[i], tuple(f.shape)))

            def final(state, feed, rng_key):
                fetches, new_state, key = per_device(state, feed, rng_key)
                fetches = [jax.lax.pmean(f, axis_name) if i in pmean_idx
                           else f for i, f in enumerate(fetches)]
                return fetches, new_state, key

            in_specs = (
                jax.tree_util.tree_map(lambda _: P(), state),
                {k: (P(*((axis_name,) + (None,) * (len(v.shape) - 1)))
                     if tuple(v.shape) != tuple(feed[k].shape) else P())
                 for k, v in fd_avals.items()},
                P())
            out_specs = (f_specs,
                         jax.tree_util.tree_map(lambda _: P(), ns_shapes),
                         P())
            one = comm.shard_map(final, mesh, in_specs=in_specs,
                                 out_specs=out_specs)
            if repeat == 1:
                fn = one
            else:
                def fn(state, feed, rng_key):
                    fetches, state, rng_key = one(state, feed, rng_key)

                    def body(carry, _):
                        st, key = carry
                        f, st2, key2 = one(st, feed, key)
                        return (st2, key2), f

                    (state, rng_key), fs = jax.lax.scan(
                        body, (state, rng_key), None, length=repeat - 1)
                    return [f[-1] for f in fs], state, rng_key
            jitted = jax.jit(fn, donate_argnums=(0,),
                             in_shardings=shardings)
            # dry-run the whole build abstractly before committing: a
            # trace failure here costs nothing (no donation happened)
            jax.eval_shape(fn, st_avals,
                           {k: jax.ShapeDtypeStruct(tuple(v.shape),
                                                    v.dtype)
                            for k, v in feed.items()}, key_aval)
            return jitted

        cell = {}

        def dispatch(state, feed, rng_key):
            if "fn" not in cell:
                try:
                    built = build(state, feed, rng_key)
                    self.stats["comm_path"] = "explicit"
                    grads_tpl = capture.get("grads")
                    if grads_tpl:
                        # measured-from-the-trace: the plan built over
                        # the grads the program actually produced, not
                        # the parameter-list model
                        s = comm.plan_summary(grads_tpl, plan["policy"],
                                              axis_size=n)
                        self.stats["comm_bytes"] = s["comm_bytes"]
                        self.stats["comm_buckets"] = s["comm_buckets"]
                except Exception as e:
                    from ..resilience.events import record_event
                    record_event("comm_degraded", site="comm.gspmd",
                                 policy=plan["policy"].base, error=str(e))
                    self.stats["comm_path"] = "model"
                    cell["fn"] = fallback
                else:
                    # collective-consistency pass (PT020-PT023), same
                    # opt-in as the pre-trace verify: the explicit path
                    # just chose an ordered collective sequence per
                    # replica — prove it is the pure function of
                    # (world, policy) its peers compute. OUTSIDE the
                    # try, before caching: a verifier finding raises
                    # readably instead of degrading to GSPMD as if the
                    # routing itself had failed
                    if _verify_requested() and capture.get("grads"):
                        from ..analysis import comm_rules
                        comm_rules.verify_comm_or_raise(
                            capture["grads"], plan["policy"], axis_size=n,
                            overlap=bool(FLAGS.comm_overlap),
                            context="explicit-comm collective "
                                    "consistency")
                    import os as _os
                    if _os.environ.get("PADDLE_TPU_ELASTIC_STATE") \
                            and capture.get("grads"):
                        # elastic job start: cross-replica fingerprint
                        # exchange — divergence refuses the first
                        # collective readably (PT020), same rung as the
                        # verifier above, gated on the launch contract
                        # instead of PADDLE_TPU_VERIFY. The sharding
                        # preflight's fingerprint (when a spec table
                        # exists) folds the PT044 sharded-collective
                        # vocabulary into the exchanged digest
                        from ..elastic.fingerprints import \
                            check_replica_schedule
                        check_replica_schedule(
                            capture["grads"], policy=plan["policy"],
                            axis_size=n,
                            overlap=bool(FLAGS.comm_overlap),
                            sharding=self.stats.get(
                                "sharding_fingerprint"))
                    cell["fn"] = built
            return cell["fn"](state, feed, rng_key)

        return dispatch

    def _record_comm_model(self, program, dist):
        """Refresh the comm_* stats entries: the modelled per-step wire
        traffic of this program's DP gradient sync under the active comm
        policy (paddle_tpu.comm). A model, not a measurement — GSPMD owns
        the actual collective schedule on this path — but it is the same
        bytes model the explicit data_parallel_step_fn path realises, so
        `paddle_tpu accounting` and the profiler's comm section agree
        across both. Runs once per fresh compile."""
        from .. import comm
        from .. import profiler as _prof
        data_axis = dist.strategy.data_axis
        n = dict(dist.mesh.shape).get(data_axis, 1)
        if n <= 1:
            return
        # refreshed per compile, like every comm_* stat: an earlier
        # explicit-path program must not leave "explicit" sticking to a
        # later ineligible one (the dispatcher re-asserts "explicit" at
        # its first call, which happens after this)
        self.stats["comm_path"] = "model"
        grads_tpl = {}
        for p in program.all_parameters():
            spec = dist.specs.get(p.name)
            if [a for a in (spec or ()) if a is not None]:
                continue  # tp/ZeRO-sharded: not on the DP all-reduce path
            if not p.shape:
                continue
            try:
                dtype = np.dtype(getattr(p.dtype, "name", p.dtype) or
                                 "float32")
            except TypeError:
                continue
            grads_tpl[p.name] = jax.ShapeDtypeStruct(tuple(p.shape), dtype)
        if not grads_tpl:
            return
        from ..resilience.faults import FaultError
        policy = comm.resolve_policy(axis_size=n)
        try:
            summary = comm.plan_summary(grads_tpl, policy, axis_size=n)
        except (FaultError, ValueError):
            # observability must never kill the run: an armed
            # comm.bucket_roundtrip fault or an axis/hosts mismatch only
            # costs the byte model on this GSPMD path (the collectives
            # themselves are GSPMD-derived, not comm-built)
            return
        self.stats["comm_bytes"] = summary["comm_bytes"]
        self.stats["comm_buckets"] = summary["comm_buckets"]
        _prof.update_comm_counters(
            comm_builds=1, comm_bytes=summary["comm_bytes"],
            comm_buckets=summary["comm_buckets"],
            comm_dispatches=summary["comm_dispatches"],
            comm_payload_bytes=summary["comm_payload_bytes"])

    def _compile(self, program, feed_template, fetch_names, state_names,
                 shardings=None, dist=None, repeat=1):
        # first compile in the process configures jax's on-disk XLA cache
        # (~/.cache/paddle_tpu/xla by default; FLAGS.compile_cache=0 opts
        # out) so repeat runs skip the cold compile entirely
        from ..pipeline import maybe_enable_compile_cache
        maybe_enable_compile_cache()
        block = program.global_block()
        persist = self._persistable_names(program)
        written = {n for op_ in _iter_ops(block) for n in op_.output_arg_names}
        # persistables created by this program (e.g. startup init ops) join
        # the state outputs even though they weren't state inputs
        extra_out = sorted((written & persist) - set(state_names)
                           - set(feed_template))

        value_hook = None
        if dist is not None:
            def value_hook(name, value):
                # pin named intermediates (notably @GRAD vars) to their
                # assigned spec so GSPMD reduce-scatters where ZeRO shards
                if name in dist.specs and hasattr(value, "ndim"):
                    return jax.lax.with_sharding_constraint(
                        value, dist.sharding_for(name, value))
                return value

        def one_step(state, feed, rng_key):
            env = dict(feed)
            env.update(state)
            rng = RngSource(rng_key)
            trace_ops(block, env, rng, value_hook)
            # every state input passes through (unwritten entries alias their
            # donated input buffer; written ones carry the update). Persisted
            # state must not hold ConcreteScalar: its python value is pytree
            # *aux* data, so a changing counter would re-specialise (retrace
            # + recompile) the whole step every run.
            new_state = {n: raw_data(env[n]) if isinstance(
                env[n], ConcreteScalar) else env[n] for n in state_names}
            for n in extra_out:
                if n in env:
                    v = env[n]
                    new_state[n] = raw_data(v) if isinstance(
                        v, ConcreteScalar) else v
            fetches = [env[n] for n in fetch_names]
            return fetches, new_state, rng.key

        if repeat == 1:
            fn = one_step
        else:
            def fn(state, feed, rng_key):
                # first step outside the scan: it may add extra_out keys,
                # after which the carry structure is stable
                fetches, state, rng_key = one_step(state, feed, rng_key)

                def body(carry, _):
                    st, key = carry
                    f, st2, key2 = one_step(st, feed, key)
                    return (st2, key2), f

                (state, rng_key), fs = jax.lax.scan(
                    body, (state, rng_key), None, length=repeat - 1)
                fetches = [f[-1] for f in fs]  # last step's fetches
                return fetches, state, rng_key

        if shardings is not None:
            jitted = jax.jit(fn, donate_argnums=(0,), in_shardings=shardings)
        else:
            jitted = jax.jit(fn, donate_argnums=(0,))
        from .. import profiler as _prof
        if _prof.profiler_enabled():
            # AOT-compile so the timeline artifact gets XLA's compiled cost
            # analysis + collective census for this program
            # (device_tracer.h role; see profiler.write_timeline)
            label = "program_%d" % program._uid
            mesh_devices = (dist.num_devices if dist is not None else 1)

            memo = {}

            def profiled(state, feed, rng_key):
                if "c" not in memo:
                    _prof.set_phase("trace")
                    try:
                        memo["c"] = jitted.lower(state, feed,
                                                 rng_key).compile()
                    finally:
                        _prof.set_phase("eager")
                    _prof.record_program_analysis(label, memo["c"],
                                                  mesh_devices)
                    memo["entry"] = _prof.get_program_analysis(label)
                else:
                    # O(1) re-insert so reset_profiler() between sessions
                    # doesn't lose the programs section (the expensive HLO
                    # scan ran once at compile time)
                    _prof.put_program_analysis(label, memo["entry"])
                return memo["c"](state, feed, rng_key)

            return profiled
        if dist is not None:
            # (4) of the comm tentpole: eligible pure-DP programs route
            # their grad sync through the explicit comm collectives; the
            # dispatcher degrades to the plain GSPMD jit at first call
            # if the build cannot hold the contract
            plan = self._explicit_comm_plan(program, block, dist,
                                            feed_template)
            if plan is not None:
                return self._compile_explicit_comm(
                    program, block, dist, plan, feed_template,
                    fetch_names, state_names, extra_out, shardings,
                    repeat, jitted)
        return jitted

    # -- helpers ---------------------------------------------------------------
    def _maybe_verify(self, program):
        """Opt-in pre-trace static check (PADDLE_TPU_VERIFY=1 or
        FLAGS.verify): a malformed program raises ONE readable
        ProgramVerifyError listing every diagnostic, instead of the
        cryptic jax error the trace would hit later. Runs once per
        (program uid, version)."""
        if not _verify_requested():
            return
        key = (program._uid, program._version)
        if key in self._verified:
            return
        from ..analysis import render_diagnostics, verify_or_raise
        diags = verify_or_raise(program, context="pre-trace verify")
        if diags:  # warnings only (errors raised above): surface once
            import warnings
            warnings.warn("program %d verification warnings:\n%s"
                          % (program._uid, render_diagnostics(diags)),
                          RuntimeWarning)
        self._verified.add(key)

    def _memory_preflight(self, program, feed, state, fetch_names, dist):
        """Opt-in pre-compile memory check (PADDLE_TPU_VERIFY, PT030):
        price the step's residency from the REAL array sizes (state +
        feed buffers exact, IR-declared shapes for the activations and
        gradients in between) and raise a readable ProgramVerifyError
        with the residency table when the predicted peak exceeds the
        budget (FLAGS.memory_budget_gb, or the device's detected
        bytes_limit). The estimate ignores XLA fusion/remat — a lower
        bound, which is the right direction for a refusal gate."""
        from ..analysis import memory as _mem

        def nbytes_of(v):
            if isinstance(v, TracedLoD):
                return getattr(v.data, "nbytes", None)
            return getattr(v, "nbytes", None)

        dp = 1
        mesh_shape = {}
        if dist is not None:
            mesh_shape = dict(dist.mesh.shape)
            dp = mesh_shape.get(dist.strategy.data_axis, 1)
        # budget autodetect must work on the mesh too: a pod's device
        # exposes bytes_limit exactly where OOM matters most
        budget = _mem.resolve_budget_bytes(
            device=(dist.mesh.devices.flat[0] if dist is not None
                    else self._device()))
        sizes = {}
        for n, v in state.items():
            nb = nbytes_of(v)
            if not nb:
                continue
            if dist is not None:
                # nbytes is the GLOBAL logical size; a ZeRO/tp-sharded
                # var costs each device only its shard — pricing it
                # replicated would spuriously refuse a fitting job
                spec = dist.specs.get(n)
                for axis in (a for a in (spec or ()) if a is not None):
                    nb //= max(mesh_shape.get(axis, 1), 1)
            sizes[n] = nb
        batch = None
        block = program.global_block()
        for n, v in feed.items():
            shape = tuple(getattr(v, "shape", ()) or ())
            declared = block._find_var_recursive(n)
            if (shape and declared is not None and declared.shape
                    and int(declared.shape[0]) == -1):
                batch = max(batch or 0, int(shape[0]))
            nb = nbytes_of(v)
            if nb and dp == 1:
                sizes[n] = nb  # under a mesh the feed shards: let the
                # declared shape price the per-device slice instead
        plan = _mem.verify_memory_or_raise(
            program, budget, batch=batch, fetches=fetch_names, dp=dp,
            sizes_override=sizes,
            context="executor memory preflight (before jit compile, "
                    "program %d)" % program._uid)
        from .. import profiler as _prof
        # the measured half of the predicted-vs-actual pair the
        # timeline's memory section documents: live buffers at this
        # step boundary (state + feeds are in; the compile hasn't run).
        # Once per fresh compile, never per step
        _prof.update_memory_counters(
            mem_preflights=1, mem_predicted_peak_bytes=plan.peak_bytes,
            mem_measured_live_bytes=_mem.measure_live_bytes())
        self.stats["mem_predicted_peak_bytes"] = plan.peak_bytes
        return plan

    def _sharding_preflight(self, program, dist):
        """Opt-in pre-compile sharding check (PADDLE_TPU_VERIFY,
        PT040-PT045): propagate the program's PartitionSpecs through
        one IR walk and raise a readable ProgramVerifyError — plan
        table included — BEFORE the jit compile, instead of letting
        GSPMD silently insert the resharding collectives a wrong spec
        implies. Only runs when the program carries specs (pure
        single-device programs pay nothing)."""
        specs = getattr(program, "_shardings", None)
        if not specs:
            return
        mesh_shape = None
        if dist is not None:
            mesh_shape = dict(dist.mesh.shape)
        elif getattr(program, "_mesh_axes", None):
            mesh_shape = dict(program._mesh_axes)
        if not mesh_shape:
            return  # specs with no mesh: nothing to check them against
        from ..analysis import sharding as _shard
        plan, diags = _shard.verify_sharding_or_raise(
            program, mesh_shape=mesh_shape,
            context="executor sharding preflight (before jit compile, "
                    "program %d)" % program._uid)
        if any(not d.is_error for d in diags):
            import warnings
            from ..analysis import render_diagnostics
            warnings.warn(
                "program %d sharding preflight warnings:\n%s"
                % (program._uid,
                   render_diagnostics([d for d in diags
                                       if not d.is_error])),
                RuntimeWarning)
        self.stats["sharding_fingerprint"] = plan.fingerprint
        return plan

    def _persistable_names(self, program):
        return {v.name for v in program.list_vars() if v.persistable}

    def _state_inputs(self, program, scope, feed):
        refd = _referenced_names(program.global_block())
        persist = self._persistable_names(program)
        names = []
        for n in sorted(refd):
            if n in feed:
                continue
            if n in persist and scope.has_var(n) and scope.find_var(n) is not None:
                names.append(n)
        return names

    def _rng_key(self, program, scope):
        k = scope.find_var(RNG_VAR)
        if k is None:
            seed = program.random_seed if program.random_seed is not None else 0
            k = jax.random.PRNGKey(seed)
            scope.set_var(RNG_VAR, k)
        return k

    def _writeback(self, program, scope, env, rng_key):
        persist = self._persistable_names(program)
        for n, v in env.items():
            if n in persist:
                # scope never holds ConcreteScalar (see one_step new_state)
                scope.set_var(n, raw_data(v) if isinstance(v, ConcreteScalar)
                              else v)
        scope.set_var(RNG_VAR, rng_key)

    def close(self):
        self._cache.clear()


def _iter_ops(block):
    for op in block.ops:
        yield op
        for a in _op_sub_blocks(op):
            for sub in _iter_ops(a):
                yield sub


# module-level convenience mirroring fluid.executor
def fetch_var(name, scope=None, return_numpy=True):
    scope = scope or global_scope()
    v = scope.find_var(name)
    if v is None:
        raise KeyError("variable %r not found in scope" % name)
    return np.asarray(v) if return_numpy and not isinstance(v, LoDTensor) else v
