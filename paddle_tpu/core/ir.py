"""Program IR: the framework's model representation.

A ``Program`` is a list of ``Block``s; a ``Block`` holds named ``Variable``s
and a sequence of ``Operator``s (reference: paddle/fluid/framework/framework.proto:19-172,
python/paddle/fluid/framework.py:117,361,644,921). The critical TPU-first
departure: the reference *interprets* a block op-by-op in C++
(reference: paddle/fluid/framework/executor.cc:125-144); here the whole block is
traced into ONE jitted XLA computation by ``paddle_tpu.core.executor`` — ops
are symbolic nodes lowered to jax, never dispatched individually at runtime.
"""
from __future__ import annotations

import contextlib
import copy
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import types, unique_name
from .types import VarType, convert_dtype

GRAD_SUFFIX = "@GRAD"

# per-program cap on recorded build-time diagnostics (shape-infer failures,
# create_var conflicts): enough to debug with, never unbounded growth for a
# long-lived program that keeps appending ops
SHAPE_INFER_FAILURE_CAP = 64


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def sub_block_read_names(op: "Operator", program: "Program") -> set:
    """All names a control-flow op's sub-blocks read (recursive, cycle-safe):
    keeping the op must keep its body's upstream producers. Shared by
    Program.prune and the analysis dead-op rule — the sub-block attr
    conventions (Block values, or int under 'sub_block'/'block') live here
    in one place."""

    def subs(o):
        for key, a in o.attrs.items():
            if isinstance(a, Block) and a.program is program:
                yield a
            elif isinstance(a, int) and not isinstance(a, bool) \
                    and key in ("sub_block", "block") \
                    and 0 <= a < len(program.blocks):
                yield program.blocks[a]

    names = set()
    seen = set()
    stack = list(subs(op))
    while stack:
        blk = stack.pop()
        if blk.idx in seen:  # corrupt programs may cycle; never recurse off
            continue
        seen.add(blk.idx)
        for sop in blk.ops:
            names.update(n for n in sop.input_arg_names if n)
            stack.extend(subs(sop))
    return names


class Variable(object):
    """Symbolic variable inside a Block.

    reference: python/paddle/fluid/framework.py:117 (class Variable).
    ``shape`` may contain -1 for the batch dim (resolved at feed time; XLA
    still compiles static — distinct feed shapes hit the executor's compile
    cache separately, which replaces the reference's fully-dynamic shapes).
    """

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 lod_level=0, persistable=False, stop_gradient=False,
                 type=VarType.LOD_TENSOR, initializer=None, **kwargs):
        self.block = block
        self.name = name if name is not None else unique_name.generate("_generated_var")
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype) if type == VarType.LOD_TENSOR else dtype
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = type
        self.op = None  # producing operator, set by Block.append_op

    # -- convenience mirroring the reference Python Variable API ------------
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def numel(self):
        if self.shape is None:
            return None  # shape not yet known (pre-inference var)
        n = 1
        for d in self.shape:
            n *= max(d, 1) if d != -1 else 1
        return n

    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s, lod=%s%s)" % (
            self.name, self.shape, getattr(self.dtype, "name", self.dtype),
            self.lod_level, ", persistable" if self.persistable else "")

    __str__ = __repr__

    # operator sugar (reference exposes this via math_op_patch.py)
    def _binary(self, other, op):
        from ..layers import math_op_patch
        return math_op_patch.binary(self, other, op)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        from ..layers import math_op_patch
        return math_op_patch.binary(self, other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    __div__ = __truediv__

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")

    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")


class Parameter(Variable):
    """Trainable variable (reference: python/paddle/fluid/framework.py:1082)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        super(Parameter, self).__init__(block, shape=shape, dtype=dtype, **kwargs)


class Operator(object):
    """One op node: type + named input/output slots + attrs.

    reference: python/paddle/fluid/framework.py:361 (class Operator),
    paddle/fluid/framework/framework.proto:55-73 (OpDesc). Attrs may include
    sub-Blocks (control flow), matching attr type BLOCK.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        # slot -> list[str] of var names
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, Any] = dict(attrs or {})

        def _names(v):
            if v is None:
                return []
            if isinstance(v, (list, tuple)):
                return [x.name if isinstance(x, Variable) else x for x in v]
            return [v.name if isinstance(v, Variable) else v]

        for slot, v in (inputs or {}).items():
            self.inputs[slot] = _names(v)
        for slot, v in (outputs or {}).items():
            self.outputs[slot] = _names(v)

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name):
        return name in self.attrs

    def __repr__(self):
        ins = ", ".join("%s=%s" % kv for kv in sorted(self.inputs.items()))
        outs = ", ".join("%s=%s" % kv for kv in sorted(self.outputs.items()))
        return "{%s} = %s(%s)" % (outs, self.type, ins)


class Block(object):
    """Vars + op list; chains to a parent for control-flow sub-blocks.

    reference: python/paddle/fluid/framework.py:644 (class Block).
    """

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self):
        # out-of-range guards the lookups the verifier runs on corrupt
        # programs (it reports the bad index as PT010 instead of crashing)
        if self.parent_idx < 0 or self.parent_idx >= len(self.program.blocks):
            return None
        return self.program.blocks[self.parent_idx]

    # -- var management ----------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            existing = self.vars[name]
            self._check_var_redefinition(existing, kwargs)
            return existing
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def _check_var_redefinition(self, existing, kwargs):
        """create_var on an existing name returns the existing var; if the
        request carried a conflicting shape/dtype that silent return hides
        a real bug — warn and record it for the PT012 verifier rule."""
        conflicts = []
        shape = kwargs.get("shape")
        if shape is not None and existing.shape is not None:
            req = tuple(shape)
            cur = tuple(existing.shape)
            # -1 is the batch wildcard: only fixed dims can conflict
            if len(req) != len(cur) or any(
                    a != b for a, b in zip(cur, req)
                    if a != -1 and b != -1):
                conflicts.append(("shape", cur, req))
        dtype = kwargs.get("dtype")
        if dtype is not None and existing.type == VarType.LOD_TENSOR \
                and kwargs.get("type", VarType.LOD_TENSOR) \
                == VarType.LOD_TENSOR:
            req_dt = convert_dtype(dtype)
            if req_dt != existing.dtype:
                conflicts.append(("dtype", existing.dtype, req_dt))
        if not conflicts:
            return
        rec = getattr(self.program, "_var_def_conflicts", None)
        if rec is None:
            rec = self.program._var_def_conflicts = []
        import warnings
        for field, cur, req in conflicts:
            if len(rec) < SHAPE_INFER_FAILURE_CAP:
                rec.append((self.idx, existing.name, field, cur, req))
            warnings.warn(
                "create_var(%r) requested %s %s but an existing var with "
                "%s %s was returned" % (existing.name, field, req, field,
                                        cur), RuntimeWarning)

    def create_parameter(self, **kwargs) -> Parameter:
        shape = kwargs.pop("shape")
        dtype = kwargs.pop("dtype", "float32")
        param = Parameter(self, shape, dtype, **kwargs)
        # parameters always live in the global (root) block, like the reference
        gb = self.program.global_block()
        gb.vars[param.name] = param
        param.block = gb
        return param

    def var(self, name) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError("Variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name) -> bool:
        return self._find_var_recursive(name) is not None

    def has_var_local(self, name) -> bool:
        return name in self.vars

    def _find_var_recursive(self, name) -> Optional[Variable]:
        blk = self
        seen = set()  # a corrupt parent chain may cycle; never hang on it
        while blk is not None and blk.idx not in seen:
            if name in blk.vars:
                return blk.vars[name]
            seen.add(blk.idx)
            blk = blk.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- op management -----------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        for slot, names in op.outputs.items():
            for n in names:
                v = self._find_var_recursive(n)
                if v is not None:
                    v.op = op
        self._infer_shape(op)
        self.program._bump_version()
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self._infer_shape(op)
        self.program._bump_version()
        return op

    def prepend_op(self, **kwargs) -> Operator:
        return self.insert_op(0, **kwargs)

    def _infer_shape(self, op):
        from . import registry
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.infer_shape is not None:
            try:
                opdef.infer_shape(op, self)
            except Exception as e:
                # best-effort (real shapes come from tracing) but never
                # silent: the failure is recorded for debugging (bounded —
                # analysis.verify surfaces the record as PT013
                # diagnostics), and PADDLE_TPU_DEBUG_SHAPES=1 surfaces it
                # immediately — otherwise shape bugs appear only as
                # cryptic trace errors
                import os
                rec = getattr(self.program, "_shape_infer_failures", None)
                if rec is None:
                    rec = self.program._shape_infer_failures = []
                if len(rec) < SHAPE_INFER_FAILURE_CAP:
                    rec.append((op.type, str(e)))
                else:
                    self.program._shape_infer_dropped = getattr(
                        self.program, "_shape_infer_dropped", 0) + 1
                from ..flags import FLAGS
                if (os.environ.get("PADDLE_TPU_DEBUG_SHAPES")
                        or FLAGS.debug_shapes):
                    import warnings
                    warnings.warn("shape inference failed for %s: %s"
                                  % (op, e), RuntimeWarning)

    def __repr__(self):
        lines = ["Block %d (parent %d):" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)


class Program(object):
    """The model: a list of Blocks, block 0 global.

    reference: python/paddle/fluid/framework.py:921 (class Program). The pair
    convention (startup program holding init ops, main program holding the
    train/infer graph) is preserved — see ``default_startup_program`` /
    ``default_main_program`` below.
    """

    _uid_counter = [0]

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self._version = 0
        Program._uid_counter[0] += 1
        self._uid = Program._uid_counter[0]  # stable executor cache identity
        self._seed = None  # program-level RNG seed (None -> executor default)
        # sharding annotations: var name -> jax PartitionSpec-like tuple,
        # attached by paddle_tpu.parallel (the transpiler-as-sharding-pass)
        self._shardings: Dict[str, Any] = {}
        # mesh annotation: axis name -> size, attached alongside
        # _shardings so analysis.sharding can check specs against the
        # mesh they were written for without a live jax Mesh
        self._mesh_axes: Dict[str, int] = {}
        self._is_distributed = False

    # -- block management --------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def create_block(self, parent_idx=None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self._current_block_idx = blk.idx
        return blk

    def rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, s):
        self._seed = s

    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def clone(self, for_test=False) -> "Program":
        """Deep-copy the program (reference: framework.py Program.clone).

        ``for_test=True`` flips ops' ``is_test`` attr (dropout/batch_norm
        behave in inference mode), matching reference ``inference_optimize``.
        """
        p = copy.deepcopy(self)
        Program._uid_counter[0] += 1
        p._uid = Program._uid_counter[0]
        if for_test:
            for blk in p.blocks:
                for op in blk.ops:
                    if "is_test" in _TEST_SENSITIVE_OPS.get(op.type, ()):
                        op.attrs["is_test"] = True
        return p

    def prune(self, feeds: Sequence[str], fetches: Sequence[str]) -> "Program":
        """Dead-op elimination for inference export.

        reference: paddle/fluid/framework/prune.cc + io.py:295
        (save_inference_model prunes to feed/fetch targets).
        """
        p = self.clone(for_test=True)
        blk = p.global_block()

        needed = set(fetches)
        kept = []
        for op in reversed(blk.ops):
            if set(op.output_arg_names) & needed:
                kept.append(op)
                needed |= set(op.input_arg_names)
                needed |= sub_block_read_names(op, p)
        blk.ops = list(reversed(kept))
        return p

    def to_string(self, throw_on_error=False):
        return "\n".join(repr(b) for b in self.blocks)

    __str__ = to_string
    __repr__ = to_string


_TEST_SENSITIVE_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
    "lrn": ("is_test",),
    "nce": ("is_test",),
}

# -- default program pair (reference: framework.py bottom) -------------------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """reference: python/paddle/fluid/framework.py program_guard."""
    global _main_program, _startup_program
    old_main, old_start = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_main, old_start


def switch_main_program(program):
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program):
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old
