"""Program-to-program autodiff.

reference: python/paddle/fluid/backward.py:270,345,422,551 (_append_backward_ops_,
sum-op insertion for multi-consumer grads, append_backward, calc_gradient) and
paddle/fluid/framework/backward.cc:246 — per-op GradOpDescMakers emit grad
OpDescs walked in reverse, with gradient accumulation via inserted ``sum`` ops.

TPU-first twist: instead of ~200 hand-written grad kernels, the default grad
maker emits ONE generic grad op whose lowering replays the forward op's jax
lowering under ``jax.vjp`` (see ops/generic_grad.py). The *program structure*
(grad ops in the block, ``X@GRAD`` naming, sum-merge, no_grad sets,
stop_gradient) matches the reference contract exactly — so optimizer-as-ops,
clipping and regularization compose identically — while the math is derived
by XLA from the same code path that runs forward.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from . import ir, registry, unique_name
from .ir import grad_var_name
from .types import is_floating


def _op_path_to_loss(block: ir.Block, loss_name: str) -> List[int]:
    """Indices of ops that (transitively) contribute to the loss."""
    needed = {loss_name}
    path = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if set(op.output_arg_names) & needed:
            path.append(i)
            needed |= set(op.input_arg_names)
    return list(reversed(path))


def default_grad_maker(op: ir.Operator, block: ir.Block,
                       grad_of: Dict[str, str], no_grad: Set[str]):
    """Build the generic vjp grad op desc for a forward op.

    Grad op inputs: every forward input slot (same names), every forward
    output slot, plus ``<out_slot>@GRAD`` slots bound to the accumulated
    gradient vars of the outputs. Outputs: ``<in_slot>@GRAD`` for inputs that
    are floating-point and not suppressed.
    """
    inputs = {s: list(ns) for s, ns in op.inputs.items()}
    out_slots = list(op.outputs)
    in_slots = list(op.inputs)
    diff_slots = {}
    any_outgrad = False
    for s in out_slots:
        inputs[s] = list(op.outputs[s])
        gnames = []
        for n in op.outputs[s]:
            g = grad_of.get(n)
            gnames.append(g if g is not None else "")
            if g is not None:
                any_outgrad = True
        inputs[s + "@GRAD"] = gnames
    if not any_outgrad:
        return None
    outputs = {}
    for s in in_slots:
        gout = []
        want = []
        for n in op.inputs[s]:
            var = block._find_var_recursive(n)
            ok = (n not in no_grad
                  and var is not None
                  and not var.stop_gradient
                  and (var.dtype is None or is_floating(var.dtype)))
            want.append(ok)
            gout.append(grad_var_name(n) if ok else "")
        if any(want):
            outputs[s + "@GRAD"] = gout
            diff_slots[s] = want
    if not outputs:
        return None
    attrs = dict(op.attrs)
    attrs["__fwd_type__"] = op.type
    attrs["__fwd_input_slots__"] = in_slots
    attrs["__fwd_output_slots__"] = out_slots
    attrs["__diff_slots__"] = diff_slots
    return [("generic_grad", inputs, outputs, attrs)]


def _make_grad_vars(block: ir.Block, op_descs):
    for (_, _, outputs, _) in op_descs:
        for names in outputs.values():
            for n in names:
                if n and not block.has_var(n):
                    fwd = n[:-len(ir.GRAD_SUFFIX)] if n.endswith(ir.GRAD_SUFFIX) else None
                    fv = block._find_var_recursive(fwd) if fwd else None
                    block.create_var(
                        name=n,
                        shape=fv.shape if fv is not None else None,
                        dtype=fv.dtype if fv is not None else "float32",
                        lod_level=fv.lod_level if fv is not None else 0)


def append_backward(loss: ir.Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None) -> List[Tuple[ir.Parameter, ir.Variable]]:
    """reference: python/paddle/fluid/backward.py:422 (append_backward).

    Returns (parameter, gradient) pairs for the optimizer, after appending
    grad ops (and accumulation ``sum`` ops) to the loss's program.
    """
    block = loss.block
    program = block.program
    no_grad: Set[str] = set(no_grad_set or ())
    for v in program.list_vars():
        if v.stop_gradient:
            no_grad.add(v.name)

    # d(loss)/d(loss) = 1
    loss_grad = grad_var_name(loss.name)
    block.create_var(name=loss_grad, shape=loss.shape or (1,), dtype=loss.dtype)
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={"shape": list(loss.shape or (1,)), "value": 1.0,
               "dtype": str(loss.dtype), "force_cpu": False})

    path = _op_path_to_loss(block, loss.name)
    grad_of: Dict[str, str] = {loss.name: loss_grad}
    produced: Dict[str, int] = {}

    for i in reversed(path):
        op = block.ops[i]
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.no_gradient:
            continue
        maker = (opdef.grad_maker if opdef is not None and opdef.grad_maker
                 else default_grad_maker)
        descs = maker(op, block, grad_of, no_grad)
        if not descs:
            continue
        # rename duplicate grad outputs + accumulate with sum ops
        final_descs = []
        for (gtype, gin, gout, gattrs) in descs:
            sums = []
            for slot, names in gout.items():
                for j, n in enumerate(names):
                    if not n:
                        continue
                    fwd_name = n[:-len(ir.GRAD_SUFFIX)]
                    if fwd_name in grad_of and grad_of[fwd_name] is not None:
                        # another consumer already contributed: rename + sum
                        renamed = unique_name.generate(n + "@RENAME")
                        names[j] = renamed
                        fv = block._find_var_recursive(fwd_name)
                        block.create_var(name=renamed,
                                         shape=fv.shape if fv else None,
                                         dtype=fv.dtype if fv else "float32")
                        acc = unique_name.generate(n + "@ACC")
                        block.create_var(name=acc,
                                         shape=fv.shape if fv else None,
                                         dtype=fv.dtype if fv else "float32")
                        sums.append(
                            ("sum", {"X": [grad_of[fwd_name], renamed]},
                             {"Out": [acc]}, {}))
                        grad_of[fwd_name] = acc
                    else:
                        grad_of[fwd_name] = n
            final_descs.append((gtype, gin, gout, gattrs))
            final_descs.extend(sums)  # grad op runs before its accumulations
        _make_grad_vars(block, final_descs)
        for (gtype, gin, gout, gattrs) in final_descs:
            block.append_op(type=gtype, inputs=gin, outputs=gout, attrs=gattrs)

    # canonicalise: X@GRAD name should hold the final accumulated grad
    params = (parameter_list if parameter_list is not None
              else [p.name for p in program.all_parameters()
                    if getattr(p, "trainable", True)])
    params_and_grads = []
    for pname in params:
        p = block._find_var_recursive(pname)
        g = grad_of.get(pname)
        if g is None or pname in no_grad:
            continue
        if g != grad_var_name(pname):
            # alias final accumulator to the canonical grad name
            canon = grad_var_name(pname)
            if not block.has_var(canon):
                block.create_var(name=canon, shape=p.shape, dtype=p.dtype)
            block.append_op(type="assign", inputs={"X": [g]},
                            outputs={"Out": [canon]})
            g = canon
        params_and_grads.append((p, block.var(g)))
    _check_backward_pass(program)
    return params_and_grads


def _check_backward_pass(program):
    """Always-on post-pass self-check (the soaked ROADMAP item): the
    cheap structural rules prove backward kept the graph well-formed,
    and PT007 catches an orphan ``@GRAD`` at the point gradients are
    created — a rename/prune half-applied here would otherwise only
    surface at lint time (or as a wrong optimizer update). Structural
    ERRORs raise; the warning-severity PT007 findings surface as one
    python warning."""
    import warnings

    from ..analysis import check_after_pass, render_diagnostics
    diags = check_after_pass(program, "append_backward",
                             extra_rules=("PT007",))
    orphans = [d for d in diags if d.code == "PT007"]
    if orphans:
        warnings.warn("append_backward left orphan gradient vars:\n%s"
                      % render_diagnostics(orphans), RuntimeWarning)


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: backward.py:551 — gradients of targets wrt arbitrary inputs."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, "calc_gradient currently supports one target"
    pg = append_backward(targets[0],
                         parameter_list=[v.name for v in inputs],
                         no_grad_set=no_grad_set)
    by_name = {p.name: g for p, g in pg}
    return [by_name.get(v.name) for v in inputs]
