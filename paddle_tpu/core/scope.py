"""Scope: the runtime name -> value store.

reference: paddle/fluid/framework/scope.h:38 (hierarchical Scope) and
variable.h (type-erased Variable). Here values are jax Arrays (device
buffers), host ``LoDTensor``s, numpy arrays, or arbitrary host objects (RAW).
Hierarchy is kept for control-flow/step scopes and the ``global_scope()``
singleton matches executor.py's.
"""
from __future__ import annotations

from typing import Any, Dict, Optional


class Scope(object):
    def __init__(self, parent: "Scope" = None):
        self.parent = parent
        self._vars: Dict[str, Any] = {}
        self._kids = []
        # bumped when the VARIABLE SET changes (new name added/removed) —
        # executors key their state-signature memo on it; value updates
        # don't bump (shapes/dtypes of existing entries are re-validated
        # only when the set changes, which is when new persistables appear)
        self._names_version = 0

    def var(self, name: str):
        """Find-or-create (reference: Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
            self._names_version += 1
        return self._vars[name]

    def find_var(self, name: str):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name: str) -> bool:
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def set_var(self, name: str, value):
        # write-through to the scope that owns the name, else local
        s = self
        while s is not None:
            if name in s._vars:
                s._vars[name] = value
                return
            s = s.parent
        self._vars[name] = value
        self._names_version += 1

    def erase(self, name: str):
        if name in self._vars:
            self._names_version += 1
        self._vars.pop(name, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def local_var_names(self):
        return list(self._vars)

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return _guard()
