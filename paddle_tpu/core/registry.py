"""Op registry: op type -> (jax lowering, shape inference, grad maker).

Replaces the reference's static-registrar macro system
(reference: paddle/fluid/framework/op_registry.h:127-196 REGISTER_OPERATOR /
REGISTER_OP / REGISTER_OP_*_KERNEL and op_info.h OpInfoMap). Where the
reference registers per-(place, dtype, layout, library) kernels, here one jax
lowering serves all places — XLA does the per-backend codegen — so the
"kernel" axis collapses to a single ``lower`` function, optionally shadowed by
a Pallas implementation for hot ops.

Gradients: ops may register an explicit ``grad`` maker (emitting grad OpDescs
like the reference's GradOpDescMaker, op_registry.h:148), but the default is
the *generic vjp* maker — the grad op replays the forward lowering under
``jax.vjp``. This is the TPU-native answer to the reference's hand-written
grad kernels: XLA differentiates the same code path it compiles.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

_REGISTRY: Dict[str, "OpDef"] = {}


class OpDef(object):
    __slots__ = ("type", "lower", "infer_shape", "grad_maker", "host",
                 "stateful_outputs", "custom_grad_lower", "no_gradient")

    def __init__(self, type, lower=None, infer_shape=None, grad_maker=None,
                 host=False, stateful_outputs=(), no_gradient=False):
        self.type = type
        self.lower = lower
        self.infer_shape = infer_shape
        self.grad_maker = grad_maker          # fn(op, block, grad_map) -> [Operator descs]
        # must run eagerly on host (save/load/py_func). Either a bool or a
        # predicate fn(op)->bool for ops that are host-only under certain
        # attrs (e.g. sequence_pool with stride windows)
        self.host = host
        self.stateful_outputs = tuple(stateful_outputs)  # output slots aliasing inputs (in-place state)
        self.no_gradient = no_gradient


def register_op(type, infer_shape=None, grad_maker=None, host=False,
                stateful_outputs=(), no_gradient=False):
    """Decorator registering ``fn`` as the jax lowering for op ``type``."""

    def deco(fn):
        _REGISTRY[type] = OpDef(type, lower=fn, infer_shape=infer_shape,
                                grad_maker=grad_maker, host=host,
                                stateful_outputs=stateful_outputs,
                                no_gradient=no_gradient)
        return fn

    return deco


def set_grad_maker(type, maker):
    lookup_checked(type).grad_maker = maker


def set_infer_shape(type, fn):
    lookup_checked(type).infer_shape = fn


def lookup(type) -> Optional[OpDef]:
    return _REGISTRY.get(type)


def lookup_checked(type) -> OpDef:
    opdef = _REGISTRY.get(type)
    if opdef is None:
        raise NotImplementedError(
            "Op %r has no registered lowering. Registered: %s..."
            % (type, sorted(_REGISTRY)[:20]))
    return opdef


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)
