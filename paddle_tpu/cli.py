"""Command line: ``python -m paddle_tpu
{train,bench,lint,serve,route,accounting,tune,info,convert}``.

reference: the ``paddle`` binary (paddle/trainer/TrainerMain.cpp:32 —
``paddle train``, ``paddle pserver``, ``paddle merge_model``; launch wrapper
paddle/scripts/submit_local.sh.in:173). TPU redesign: there is no pserver
role — distribution is SPMD sharding — so the surviving verbs are train
(drive a user config), bench (the benchmark harnesses), convert (dataset ->
recordio shards), info (device/platform report).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys


def _load_config(path):
    spec = importlib.util.spec_from_file_location("train_config", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def cmd_train(args):
    """Config contract: the file defines ``model()`` returning a dict with
    keys cost, feed_list, reader (and optionally optimizer, num_passes)."""
    import paddle_tpu as pt

    cfg = _load_config(args.config)
    spec = cfg.model()
    optimizer = spec.get("optimizer") or pt.optimizer.SGD(
        learning_rate=args.learning_rate)
    trainer = pt.Trainer(cost=spec["cost"], optimizer=optimizer,
                         feed_list=spec["feed_list"],
                         checkpoint_dir=args.checkpoint_dir or None)

    def handler(e):
        if isinstance(e, pt.trainer_mod.EndIteration):
            if e.batch_id % args.log_period == 0:
                print("pass %d batch %d cost %.5f"
                      % (e.pass_id, e.batch_id, e.cost))
        elif isinstance(e, pt.trainer_mod.EndPass):
            print("pass %d done: %s" % (e.pass_id, e.metrics))

    trainer.train(spec["reader"],
                  num_passes=args.num_passes or spec.get("num_passes", 1),
                  event_handler=handler)
    return 0


def cmd_bench(args):
    sys.argv = [sys.argv[0]] + (args.extra or [])
    if args.suite == "resnet":
        import os

        import bench
        # bench.py's CLI contract (batch/steps) rides env vars into the
        # device child; replicate it for `paddle_tpu bench resnet B S`
        extra = args.extra or []
        if len(extra) > 0:
            os.environ["BENCH_BATCH"] = str(int(extra[0]))
        if len(extra) > 1:
            os.environ["BENCH_STEPS"] = str(int(extra[1]))
        bench.parent_main()
    elif args.suite == "image":
        from benchmark import image_bench
        print(json.dumps(image_bench.bench(model=args.model or "resnet50",
                                           batch_size=args.batch_size)))
    elif args.suite == "rnn":
        from benchmark import rnn_bench
        print(json.dumps(rnn_bench.bench(batch_size=args.batch_size)))
    return 0


def _parse_mesh(spec, verb):
    """'dp=4,tp=2' -> {axis: size}; malformed entries — missing '=',
    non-integer or < 1 sizes, empty segments from a stray comma — are
    REJECTED with a readable message (returns None): silently skipping
    one would price/verify a different mesh than the operator asked
    for."""
    spec = (spec or "").strip()
    if not spec:
        return {}
    mesh = {}
    for pair in spec.split(","):
        k, eq, v = pair.partition("=")
        try:
            if not (eq and k.strip()):
                raise ValueError("missing '='")
            size = int(v)
            if size < 1:
                raise ValueError("size < 1")
            mesh[k.strip()] = size
        except ValueError:
            print("%s: bad --mesh entry %r (want axis=size with "
                  "size >= 1, e.g. 'dp=8' or 'dp=4,tp=2')" % (verb, pair))
            return None
    return mesh


def _append_train_step(verb, spec, main, startup):
    """Append backward + optimizer ops to ``main`` so the memory pass
    prices the TRAIN step, not just the forward build. A config with a
    cost but no optimizer gets the same default SGD ``cmd_train``
    would use — ``paddle_tpu train`` of that config runs a full
    backward, so pricing it forward-only would report a peak far below
    what the train run allocates. Returns True on success; a minimize
    failure is reported (one consistent line across the lint and
    accounting surfaces) and degrades to forward-only analysis."""
    import paddle_tpu as pt
    if not (isinstance(spec, dict) and spec.get("cost") is not None):
        return False
    optimizer = spec.get("optimizer") or pt.optimizer.SGD(
        learning_rate=0.01)
    try:
        with pt.program_guard(main, startup):
            optimizer.minimize(spec["cost"])
    except Exception as e:
        print("%s: could not append the backward (%s: %s); analysing "
              "the forward program only" % (verb, type(e).__name__, e))
        return False
    return True


def _parse_specs(pairs, verb):
    """``--spec var=dim0,dim1,...`` entries -> {var: spec tuple}. Each
    dim token is a mesh axis name, several joined with '+', or empty /
    '-' for a replicated dim (``--spec "x=dp,tp"``,
    ``--spec "w=fsdp+tp,-"``). Malformed entries are REJECTED with a
    readable message (returns None) — silently skipping one would
    verify different shardings than the operator seeded."""
    out = {}
    for pair in pairs or []:
        name, eq, spec = pair.partition("=")
        if not (eq and name.strip()):
            print("%s: bad --spec entry %r (want var=axis,axis,... with "
                  "empty or '-' for a replicated dim and '+' joining "
                  "multi-axis dims, e.g. 'x=dp,tp' or 'w=fsdp+tp,-')"
                  % (verb, pair))
            return None
        entries = []
        for tok in spec.split(","):
            tok = tok.strip()
            if not tok or tok == "-":
                entries.append(None)
            elif "+" in tok:
                entries.append(tuple(a.strip() for a in tok.split("+")
                                     if a.strip()))
            else:
                entries.append(tok)
        out[name.strip()] = tuple(entries)
    return out


def cmd_lint(args):
    """Statically verify the program a train config builds — same config
    contract as ``train`` (the file defines ``model()``) but nothing is
    executed or compiled: the Program IR is built and handed to
    paddle_tpu.analysis.verify. ``--comm`` adds the
    collective-consistency pass (PT020-PT023) over the parameter set's
    grads template at ``--comm-axis`` replicas under the comm_* flags
    (or ``--comm-policy``/``--comm-hosts`` overrides). ``--memory``
    adds the static memory planner (PT030-PT033): the backward +
    optimizer ops are appended (when the config names an optimizer) so
    the liveness pass sees the full training step, and the predicted
    per-device peak is checked against ``--budget-gb`` /
    ``FLAGS.memory_budget_gb`` at ``--batch`` over ``--mesh dp=N``.
    ``--sharding`` adds the static sharding analyzer (PT040-PT045):
    PartitionSpec propagation over ``--mesh`` (e.g.
    ``--mesh dp=4,fsdp=2,tp=2``), with ``--spec var=dp,tp`` overriding
    or seeding individual entries; when it runs, the memory pass prices
    sharded (not replicated) persistable state from the propagated
    specs. ``--all`` runs every pass with one combined summary.
    Exit 0 clean / warnings-only, 1 on error diagnostics (or any
    diagnostic with --strict), 2 if the config itself fails to build."""
    import paddle_tpu as pt
    from paddle_tpu import analysis

    if args.all:
        args.comm = args.memory = args.sharding = True
    main, startup = pt.Program(), pt.Program()
    try:
        cfg = _load_config(args.config)
        with pt.program_guard(main, startup):
            spec = cfg.model()
    except Exception as e:
        print("lint: config %r failed to build: %s: %s"
              % (args.config, type(e).__name__, e))
        return 2
    fetches = None
    if isinstance(spec, dict) and spec.get("cost") is not None:
        # metrics (accuracy etc.) count as fetch roots too: a trainer
        # fetches them per pass, so they are not dead ops
        fetches = [spec["cost"]] + list(spec.get("metrics", ()))
    diags = analysis.verify(main, fetches=fetches)
    startup_diags = analysis.verify(startup)
    comm_diags = []
    memory_diags = []
    sharding_diags = []
    sharding_plan = None
    reports = [("main program", diags), ("startup program", startup_diags)]
    train_step = None
    if args.sharding:
        from paddle_tpu.analysis import sharding as sharding_mod
        mesh = _parse_mesh(args.mesh, "lint")
        if mesh is None:
            return 2
        overrides = _parse_specs(getattr(args, "spec", None), "lint")
        if overrides is None:
            return 2
        if overrides:
            merged = dict(getattr(main, "_shardings", None) or {})
            merged.update(overrides)
            main._shardings = merged
        # the spec question is about the TRAIN step too: grads must
        # co-shard with their params (PT044) and the optimizer updates
        # are where that contract is checked
        train_step = _append_train_step("lint", spec, main, startup)
        sharding_plan, sharding_diags = sharding_mod.check_sharding(
            main, mesh_shape=mesh)
        print("sharding pass (%s program):"
              % ("train-step" if train_step else "forward-only"))
        print(sharding_plan.table())
        reports.append(("sharding pass", sharding_diags))
    if args.memory:
        from paddle_tpu.analysis import memory as memory_mod
        mesh = _parse_mesh(args.mesh, "lint")
        if mesh is None:
            return 2
        shard_specs = sharding_plan.specs if sharding_plan is not None \
            else (getattr(main, "_shardings", None) or None)
        ignored = sorted(a for a in mesh if a != "dp")
        if ignored and not shard_specs:
            # the batch shards over dp only — with no spec table the
            # params price replicated; saying so beats silently pricing
            # a different mesh than asked (run --sharding to fix)
            print("lint: --memory shards the batch over 'dp' only; "
                  "mesh axis(es) %s ignored (params priced replicated)"
                  % ", ".join(ignored))
        # the residency question is about the TRAIN step: append
        # backward + optimizer ops so activations-to-backward and
        # gradient lifetimes are in the walk (the structural rules
        # above already ran on the as-built program)
        if train_step is None:
            train_step = _append_train_step("lint", spec, main, startup)
        budget = memory_mod.resolve_budget_bytes(
            budget_gb=args.budget_gb or None)
        plan, memory_diags = memory_mod.check_memory(
            main, budget_bytes=budget, batch=args.batch,
            fetches=fetches, dp=mesh.get("dp", 1),
            specs=shard_specs, mesh_shape=mesh if shard_specs else None)
        print("memory pass (%s program):"
              % ("train-step" if train_step else "forward-only"))
        print(plan.table(budget))
        reports.append(("memory pass", memory_diags))
    if args.comm:
        from paddle_tpu.analysis import comm_rules
        from paddle_tpu import comm as comm_mod
        tpl = comm_rules.grads_template_from_program(main)
        if not tpl:
            # no row in the report either: a "clean" verdict for checks
            # that never executed would misreport the gate log
            print("comm pass: no static-shaped parameters; skipped")
        else:
            try:
                policy = comm_mod.resolve_policy(
                    base=args.comm_policy or None,
                    hosts=args.comm_hosts or None,
                    axis_size=args.comm_axis)
                comm_diags, fp = comm_rules.verify_comm(
                    tpl, policy, axis_size=args.comm_axis)
            except ValueError as e:
                print("lint: bad comm options: %s" % e)
                return 2
            print("comm pass: %d grad leaves, axis=%d, %r -> "
                  "fingerprint %s" % (len(tpl), args.comm_axis, policy,
                                      fp))
            reports.append(("comm pass", comm_diags))
    for label, ds in reports:
        report = analysis.render_diagnostics(ds, label=label)
        print(report if report else "%s: clean" % label)
    if args.dot:
        from paddle_tpu import debugger
        # errors always fill red; the PT015+ dataflow/comm families
        # highlight at any severity — their findings are exactly the
        # ops a reader wants to see on the graph
        bad_ops = {d.op_idx for d in diags + sharding_diags
                   if d.block_idx == 0 and d.op_idx is not None
                   and (d.is_error or d.code >= "PT015")}
        debugger.draw_block_graphviz(main.global_block(),
                                     op_highlights=bad_ops, path=args.dot)
        print("lint: wrote %s (%d op(s) highlighted)"
              % (args.dot, len(bad_ops)))
    all_diags = diags + startup_diags + comm_diags + memory_diags \
        + sharding_diags
    failed = any(d.is_error for d in all_diags) \
        or (args.strict and all_diags)
    if args.all:
        errs = sum(1 for d in all_diags if d.is_error)
        warns = len(all_diags) - errs
        print("lint --all: %d pass(es), %d error(s), %d warning(s) -> %s"
              % (len(reports), errs, warns,
                 "FAIL" if failed else "clean"))
    return 1 if failed else 0


def _parse_extra_models(pairs, primary=None):
    """``--extra_model name=dir`` entries -> [(name, dir)]; raises
    ValueError on a malformed pair or a name collision (two extras, or
    an extra shadowing ``primary``/``--name`` — load_model would
    silently hot-swap the earlier artifact)."""
    out = []
    seen = {primary} if primary else set()
    for pair in pairs or []:
        name, eq, dirname = pair.partition("=")
        if not (eq and name.strip() and dirname.strip()):
            raise ValueError("bad --extra_model %r (want name=dir)" % pair)
        name = name.strip()
        if name in seen:
            raise ValueError("duplicate model name %r (--extra_model "
                             "must not repeat a name or shadow --name)"
                             % name)
        seen.add(name)
        out.append((name, dirname.strip()))
    return out


def _validate_artifacts(verb, artifact_dir, extra_models, kv_pages=None,
                        page_tokens=None, draft_dir=None):
    """Validate the primary + every extra artifact up front; prints the
    problems and returns False on a bad one (nothing gets started).
    ``kv_pages``/``page_tokens``: the CLI's pool overrides — PT034 must
    size the pool the engine will ACTUALLY allocate, not the flag
    default. Beyond the per-model check, the AGGREGATE of every
    co-hosted generative model (weights + pool each) is checked
    against the budget: one process loads them all, so each fitting
    alone proves nothing. ``draft_dir`` (a ``--draft_dir`` speculation
    draft) joins the aggregate the same way — it costs its weights plus
    its own page pool; a speculative ARTIFACT needs no extra entry,
    its draft side is already priced into its own bytes."""
    from paddle_tpu import inference
    from paddle_tpu.analysis import memory as memory_mod
    budget = memory_mod.resolve_budget_bytes()
    if draft_dir and not inference.is_generative_artifact(draft_dir):
        print("%s: cannot serve: --draft_dir %r is not a generative "
              "artifact (speculation drafts are export_generative "
              "directories)" % (verb, draft_dir), file=sys.stderr)
        return False
    total, gen_labels = 0, []
    entries = [("artifact", artifact_dir)] + [
        ("extra model %r" % n, d) for n, d in extra_models]
    if draft_dir:
        entries.append(("speculation draft", draft_dir))
    for label, dirname in entries:
        generative = inference.is_generative_artifact(dirname)
        problems = (inference.validate_generative_artifact(
                        dirname, kv_pages=kv_pages,
                        page_tokens=page_tokens)
                    if generative else inference.validate_artifact(dirname))
        if problems:
            print("%s: cannot serve %s %r:" % (verb, label, dirname),
                  file=sys.stderr)
            for p in problems:
                print("  - " + p, file=sys.stderr)
            return False
        if generative and budget:
            nb = inference.generative_memory_bytes(
                dirname, kv_pages=kv_pages, page_tokens=page_tokens)
            if nb is not None:
                total += nb
                gen_labels.append("%s=%s" % (label,
                                             memory_mod.fmt_bytes(nb)))
    if budget and len(gen_labels) > 1 and total > budget:
        print("%s: cannot serve: PT034 the co-hosted generative models "
              "need %s together (%s) on a %s budget — each fits alone, "
              "one process loads them all"
              % (verb, memory_mod.fmt_bytes(total),
                 ", ".join(gen_labels), memory_mod.fmt_bytes(budget)),
              file=sys.stderr)
        return False
    return True


def cmd_serve(args):
    """Serve a compiled OR generative artifact over HTTP
    (paddle_tpu.serving): validate the artifact directory (exit 1,
    readable message, nothing started on a bad one), register + warm it
    — a generative artifact stands a continuous-batching engine up
    behind ``:generate`` — then run the JSON endpoint until
    SIGTERM/SIGINT, which drains cleanly and exits 0. Repeatable
    ``--extra_model name=dir`` entries publish additional artifacts
    from the same process (how a router replica serves a predict model
    and a generate model side by side)."""
    from paddle_tpu import inference, serving
    from paddle_tpu.flags import FLAGS

    try:
        extra_models = _parse_extra_models(args.extra_model,
                                           primary=args.name)
    except ValueError as e:
        print("serve: %s" % e, file=sys.stderr)
        return 1
    generative = inference.is_generative_artifact(args.artifact_dir)
    draft_dir = args.draft_dir or FLAGS.serve_draft_dir or None
    if draft_dir and not generative:
        print("serve: --draft_dir only pairs with a generative primary "
              "artifact", file=sys.stderr)
        return 1
    if not _validate_artifacts("serve", args.artifact_dir, extra_models,
                               kv_pages=args.kv_pages or None,
                               page_tokens=args.page_tokens or None,
                               draft_dir=draft_dir):
        return 1
    service = serving.InferenceService(
        max_batch=args.max_batch or None,
        batch_timeout_ms=(args.batch_timeout_ms
                          if args.batch_timeout_ms >= 0 else None),
        queue_depth=args.queue_depth or None,
        tier=args.tier or None)
    gen_overrides = {}
    if args.max_running:
        gen_overrides["max_running"] = args.max_running
    if args.kv_pages:
        gen_overrides["kv_pages"] = args.kv_pages
    if args.page_tokens:
        gen_overrides["page_tokens"] = args.page_tokens
    if args.prefix_sharing:
        gen_overrides["prefix_sharing"] = True
    # speculation plumbing for the PRIMARY model only: an external
    # --draft_dir loads here; a speculative artifact needs nothing —
    # the registry auto-detects and pairs it on load
    primary_overrides = dict(gen_overrides)
    if args.spec_k:
        primary_overrides["spec_k"] = args.spec_k
    loading = args.artifact_dir
    try:
        if draft_dir:
            loading = draft_dir
            primary_overrides["draft_model"] = \
                inference.load_generative(draft_dir)
            primary_overrides.setdefault("spec_k",
                                         FLAGS.serve_spec_k)
        loading = args.artifact_dir
        entry = service.load_model(
            args.name, args.artifact_dir,
            **(primary_overrides if generative else {}))
        for extra_name, extra_dir in extra_models:
            loading = extra_dir
            service.load_model(
                extra_name, extra_dir,
                **(gen_overrides
                   if inference.is_generative_artifact(extra_dir) else {}))
    except Exception as e:
        print("serve: failed to load %r: %s: %s"
              % (loading, type(e).__name__, e), file=sys.stderr)
        service.close()
        return 1
    server = serving.make_server(service, host=args.host, port=args.port)
    host, port = server.server_address[:2]
    # one parseable readiness line: smoke tests and operators read the
    # bound port from here (--port 0 binds a free one)
    info = {
        "host": host, "port": port, "model": args.name,
        "kind": "generative" if generative else "compiled",
        "version": entry.version, "warmup_ms": round(entry.warmup_ms, 3),
        "max_batch": service.max_batch,
        "batch_timeout_ms": service.batch_timeout_ms}
    if service.tier:
        info["tier"] = service.tier
    if extra_models:
        info["extra_models"] = [n for n, _ in extra_models]
    if generative:
        info.update({"max_running": entry.engine.max_running,
                     "kv_pages": entry.engine.pool.num_pages,
                     "page_tokens": entry.engine.pool.page_tokens,
                     "max_context": entry.engine.max_context})
        st = entry.engine.stats
        if st["speculative"] or st["spec_degraded"]:
            info.update({"speculative": st["speculative"],
                         "spec_k": st["spec_k"],
                         "spec_degraded": st["spec_degraded"]})
        if st.get("prefix_sharing") or st.get("prefix_degraded"):
            info.update({"prefix_sharing": st["prefix_sharing"],
                         "prefix_degraded": st["prefix_degraded"]})
    print(json.dumps({"serving": info}), flush=True)
    try:
        signum = serving.httpd.serve_until_shutdown(server)
    finally:
        # snapshot BEFORE close(): close drops the generation engines,
        # and the shutdown record is the run's serving evidence
        final_stats = service.stats
        server.server_close()
        service.close()
    print(json.dumps({"serving_stopped": {
        "signal": signum, "stats": final_stats}}), flush=True)
    return 0


def cmd_route(args):
    """Front a fleet of ``serve`` replicas with the multi-replica router
    (paddle_tpu.serving.router): validate the artifact(s), spawn and
    supervise ``--replicas`` worker processes (SIGTERM->SIGKILL drain,
    RetryPolicy restarts on crash), and run the proxy tier —
    least-loaded routing from polled /statz, health eject/probation,
    one failover retry, rolling ``:reload`` — until SIGTERM/SIGINT,
    which drains the fleet and exits 0. With ``--autoscale`` the
    closed-loop controller (paddle_tpu.serving.autoscale) grows and
    shrinks the fleet on the smoothed pressure signal within
    [--min_replicas, --max_replicas]."""
    from paddle_tpu.flags import FLAGS
    from paddle_tpu.serving import (Autoscaler, ReplicaPool, Router,
                                    httpd, make_router_server)

    if args.state_dir:
        # the audit trail: every record_durable_event() in this process
        # (router ejections/failovers, autoscale decisions, breaker
        # transitions, gray verdicts) defaults its events.jsonl here,
        # so the evidence survives a router crash
        os.makedirs(args.state_dir, exist_ok=True)
        os.environ["PADDLE_TPU_ELASTIC_STATE"] = args.state_dir
    try:
        extra_models = _parse_extra_models(args.extra_model,
                                           primary=args.name)
    except ValueError as e:
        print("route: %s" % e, file=sys.stderr)
        return 1
    if not _validate_artifacts("route", args.artifact_dir, extra_models,
                               kv_pages=args.kv_pages or None,
                               page_tokens=args.page_tokens or None,
                               draft_dir=args.draft_dir or None):
        return 1
    serve_args = []
    if args.draft_dir:
        serve_args += ["--draft_dir", args.draft_dir]
    if args.spec_k:
        serve_args += ["--spec_k", str(args.spec_k)]
    if args.max_batch:
        serve_args += ["--max_batch", str(args.max_batch)]
    if args.batch_timeout_ms >= 0:
        serve_args += ["--batch_timeout_ms", str(args.batch_timeout_ms)]
    if args.queue_depth:
        serve_args += ["--queue_depth", str(args.queue_depth)]
    if args.max_running:
        serve_args += ["--max_running", str(args.max_running)]
    if args.kv_pages:
        serve_args += ["--kv_pages", str(args.kv_pages)]
    if args.page_tokens:
        serve_args += ["--page_tokens", str(args.page_tokens)]
    if args.prefix_sharing:
        serve_args += ["--prefix_sharing"]
    for n, d in extra_models:
        serve_args += ["--extra_model", "%s=%s" % (n, d)]
    tier_counts = None
    serve_args_overrides = {}
    tier_of = {}
    if args.tiers:
        tier_counts = {}
        try:
            for part in args.tiers.split(","):
                k, _, v = part.partition("=")
                k = k.strip()
                if k not in ("prefill", "decode"):
                    raise ValueError("unknown tier %r" % k)
                tier_counts[k] = int(v)
                if tier_counts[k] < 1:
                    raise ValueError("tier %r wants >= 1 replica" % k)
        except ValueError as e:
            print("route: bad --tiers %r: %s" % (args.tiers, e),
                  file=sys.stderr)
            return 1
        if set(tier_counts) != {"prefill", "decode"}:
            print("route: --tiers wants BOTH classes, e.g. "
                  "prefill=1,decode=2", file=sys.stderr)
            return 1
        initial = sum(tier_counts.values())
        if args.replicas and args.replicas != initial:
            print("route: --tiers fixes the fleet size at %d; drop "
                  "--replicas" % initial, file=sys.stderr)
            return 1
        idx = 0
        for t in ("prefill", "decode"):
            for _ in range(tier_counts[t]):
                serve_args_overrides[idx] = ["--tier", t]
                tier_of[idx] = t
                idx += 1
        # per-tier autoscale budget: each class may grow by `headroom`
        # above its configured floor (default: double the tier)
        tier_headroom = (max(args.max_replicas - initial, 0)
                         if args.max_replicas else initial)
    elif args.autoscale:
        max_replicas = args.max_replicas or max(args.min_replicas,
                                                FLAGS.route_replicas)
        if args.min_replicas < 1 or max_replicas < args.min_replicas:
            print("route: --autoscale wants 1 <= min_replicas <= "
                  "max_replicas, got [%d, %d]"
                  % (args.min_replicas, max_replicas), file=sys.stderr)
            return 1
        initial = args.replicas or args.min_replicas
        if not args.min_replicas <= initial <= max_replicas:
            # a fleet starting outside the budget is one the controller
            # can never bring inside it (it shrinks one replica per
            # quiet window, and only when the load is quiet)
            print("route: --autoscale wants --replicas inside "
                  "[%d, %d], got %d"
                  % (args.min_replicas, max_replicas, initial),
                  file=sys.stderr)
            return 1
    else:
        initial = args.replicas or FLAGS.route_replicas
    try:
        pool = ReplicaPool(
            args.artifact_dir, initial,
            name=args.name, host=args.host, serve_args=serve_args,
            serve_args_overrides=serve_args_overrides or None,
            restart_budget=(args.restart_budget if args.restart_budget >= 0
                            else None),
            grace_sec=args.grace_sec)
        pool.start(wait=True)
    except Exception as e:
        print("route: %s" % e, file=sys.stderr)
        return 1
    router = None
    autoscalers = []
    try:
        # anything failing before the serve loop (say, the router port
        # already bound) must still drain the fleet pool.start spawned
        # — no orphan serve workers on an exception
        router = Router(pool, policy=args.policy,
                        poll_ms=args.poll_ms if args.poll_ms > 0 else None,
                        state_dir=args.state_dir or None)
        router.poll_once()
        router.start_polling()
        if args.autoscale and tier_counts:
            # one controller PER serving class, each on its
            # class-correct signal (queue depth / page occupancy)
            autoscalers = [
                Autoscaler(
                    router, pool, tier=t,
                    min_replicas=tier_counts[t],
                    max_replicas=tier_counts[t] + tier_headroom,
                    cooldown_s=(args.cooldown_s
                                if args.cooldown_s >= 0 else None))
                for t in ("prefill", "decode")]
            router.autoscaler = list(autoscalers)
            for a in autoscalers:
                a.start()
        elif args.autoscale:
            autoscalers = [Autoscaler(
                router, pool, min_replicas=args.min_replicas,
                max_replicas=max_replicas,
                up_pressure=(args.scale_up_pressure
                             if args.scale_up_pressure > 0 else None),
                down_pressure=(args.scale_down_pressure
                               if args.scale_down_pressure >= 0
                               else None),
                cooldown_s=(args.cooldown_s
                            if args.cooldown_s >= 0 else None))]
            router.autoscaler = autoscalers[0]
            autoscalers[0].start()
        server = make_router_server(router, host=args.host,
                                    port=args.port)
    except Exception as e:
        for a in autoscalers:
            a.close()
        if router is not None:
            router.close()
        pool.stop()
        print("route: %s: %s" % (type(e).__name__, e), file=sys.stderr)
        return 1
    host, port = server.server_address[:2]
    info = {
        "host": host, "port": port, "model": args.name,
        "policy": router.policy,
        "replicas": [dict({"index": w["index"], "port": w["port"],
                           "pid": w["pid"]},
                          **({"tier": tier_of[w["index"]]}
                             if w["index"] in tier_of else {}))
                     for w in pool.describe()["workers"]]}
    if tier_counts:
        info["tiers"] = dict(tier_counts)
    if len(autoscalers) == 1 and autoscalers[0].tier is None:
        a = autoscalers[0]
        info["autoscale"] = {
            "min_replicas": a.min_replicas,
            "max_replicas": a.max_replicas,
            "up_pressure": a.up_pressure,
            "down_pressure": a.down_pressure,
            "cooldown_s": a.cooldown_s}
    elif autoscalers:
        info["autoscale"] = [
            {"tier": a.tier, "min_replicas": a.min_replicas,
             "max_replicas": a.max_replicas,
             "up_pressure": a.up_pressure,
             "down_pressure": a.down_pressure,
             "cooldown_s": a.cooldown_s} for a in autoscalers]
    print(json.dumps({"router": info}), flush=True)
    try:
        signum = httpd.serve_until_shutdown(server)
    finally:
        final_stats = None
        try:
            # stats/close can take a couple of seconds (the close joins
            # the poller) — a second Ctrl-C landing there must still
            # drain the fleet, so pool.stop() is not gated on them
            for a in autoscalers:
                a.close()
            final_stats = router.stats()
            server.server_close()
            router.close()
        finally:
            pool.stop()
    print(json.dumps({"router_stopped": {
        "signal": signum, "stats": final_stats}}), flush=True)
    return 0


def cmd_accounting(args):
    """Quantify a train config's gradient-communication design: the
    per-chip collective byte counts of the transpiled parameter set
    (parallel.accounting ring formulas) plus the paddle_tpu.comm policy
    matrix — bytes-on-wire and dispatch counts for
    none/fused/hierarchical/int8 over the requested mesh — plus the
    ``memory`` columns: per-device params / optimizer state /
    activations / gradients / feeds and the predicted peak from the
    static memory planner (analysis.memory) at ``--batch``, the
    per-parameter-class sizing table the FSDP direction needs as
    input. ``--sharding`` adds the propagated-PartitionSpec plan
    (analysis.sharding): per-class spec table, fingerprint, priced
    implicit reshards, and any PT040-PT045 diagnostics as a
    ``sharding`` section. ``--generative DIR`` adds a ``kv_pool``
    section: the artifact's physical-page KV residency with
    dedup-ratio capacity columns (``--dedup-ratio``; speculative
    pairings fold the draft in). Pure analysis: nothing is compiled or
    executed, no devices needed. Same config contract as
    ``train``/``lint`` (the file defines ``model()``)."""
    import paddle_tpu as pt
    from paddle_tpu.parallel import accounting

    mesh_shape = _parse_mesh(args.mesh or "dp=8", "accounting")
    if mesh_shape is None:
        return 2
    main, startup = pt.Program(), pt.Program()
    try:
        cfg = _load_config(args.config)
        with pt.program_guard(main, startup):
            spec = cfg.model()
    except Exception as e:
        print("accounting: config %r failed to build: %s: %s"
              % (args.config, type(e).__name__, e))
        return 2
    # memory columns price the TRAIN step (optimizer slots, grads,
    # activations-to-backward); comm tables read parameters only,
    # which minimize() does not change
    train_step = _append_train_step("accounting", spec, main, startup)
    fetches = [spec["cost"]] if train_step else None
    specs = getattr(main, "_shardings", None) or {}
    try:
        report = {
            "mesh": mesh_shape,
            "collectives": accounting.collective_bytes(
                main, specs, mesh_shape),
            "comm": accounting.comm_policy_table(
                main, specs, mesh_shape, hosts=args.hosts or None,
                bucket_mb=args.bucket_mb or None,
                split_ratio=(args.split_ratio
                             if args.split_ratio >= 0 else None)),
            "memory": dict(
                accounting.memory_table(main, mesh_shape,
                                        batch=args.batch,
                                        fetches=fetches),
                train_step=train_step),
        }
        if args.generative:
            from paddle_tpu import inference as _inf
            res = _inf.generative_residency(
                args.generative, dedup_ratio=args.dedup_ratio)
            if res is None:
                print("accounting: --generative %r is not a readable "
                      "generative artifact" % args.generative)
                return 2
            report["kv_pool"] = res
        if args.sharding:
            from paddle_tpu.analysis import sharding as sharding_mod
            plan, sharding_diags = sharding_mod.check_sharding(
                main, mesh_shape=mesh_shape)
            report["sharding"] = dict(
                plan.summary(),
                diagnostics=[{"code": d.code,
                              "severity": d.severity,
                              "message": d.message,
                              "location": d.location()}
                             for d in sharding_diags])
    except ValueError as e:
        # e.g. --hosts not dividing the data axis: readable, not a trace
        print("accounting: %s" % e)
        return 2
    print(json.dumps(report, indent=2))
    return 0


def _tune_populations(program, batch, compute_dtype=None):
    """Walk the program and collect the tunable-kernel shape keys its ops
    actually hit: conv2d ops inside the conv3x3 kernel's population,
    flash_attention ops, and mul gemms inside the matmul kernel's. The
    feed batch dim (-1) substitutes ``batch``. Returns
    [(kernel, key_dict)], deduplicated, declaration order.

    ``compute_dtype`` overrides the IR-declared var dtype for the conv
    and mul keys: dispatch keys on the dtype the op RUNS at, and under
    AMP that is bfloat16 (amp.cast_inputs fires before tune.lookup), not
    the declared float32 — winners tuned at the wrong dtype would never
    hit. Defaults to bfloat16 when the program is AMP-marked."""
    from paddle_tpu.kernels.conv3x3 import supports_conv3x3
    from paddle_tpu.kernels.matmul import supports_matmul

    if compute_dtype is None and getattr(program, "_amp", False):
        compute_dtype = "bfloat16"

    def shape_of(block, name):
        v = block._find_var_recursive(name)
        if v is None or v.shape is None:
            return None
        return tuple(batch if int(s) == -1 else int(s) for s in v.shape)

    def run_dtype(block, name):
        if compute_dtype:
            return compute_dtype
        v = block._find_var_recursive(name)
        return str(getattr(v, "dtype", "float32") or "float32")

    out, seen = [], set()

    def add(kernel, key):
        k = (kernel, tuple(sorted(key.items())))
        if k not in seen:
            seen.add(k)
            out.append((kernel, key))

    for block in program.blocks:
        for op in block.ops:
            if op.type == "conv2d":
                xs = shape_of(block, op.input("Input")[0])
                ws = shape_of(block, op.input("Filter")[0])
                if not xs or not ws or len(xs) != 4:
                    continue
                s = op.attr("strides", [1, 1])
                p = op.attr("paddings", [0, 0])
                d = op.attr("dilations", [1, 1])
                g = op.attr("groups", 1) or 1
                if supports_conv3x3(ws, s, p, d, g):
                    n, c, h, w = xs
                    dt = run_dtype(block, op.input("Input")[0])
                    add("conv3x3", {"n": n, "h": h, "w": w, "c": c,
                                    "o": int(ws[0]), "dtype": dt})
            elif op.type == "flash_attention":
                qs = shape_of(block, op.input("Q")[0])
                if not qs or len(qs) != 4:
                    continue
                # no AMP override: attention_ops does not amp-cast, so
                # the op runs at the declared q dtype
                qv = block._find_var_recursive(op.input("Q")[0])
                dt = str(getattr(qv, "dtype", "float32") or "float32")
                add("flash_attention",
                    {"b": qs[0], "s": qs[1], "h": qs[2], "d": qs[3],
                     "causal": bool(op.attr("causal", False)),
                     "dtype": dt})
            elif op.type == "mul":
                xs = shape_of(block, op.input("X")[0])
                ys = shape_of(block, op.input("Y")[0])
                if not xs or not ys:
                    continue
                xn = op.attr("x_num_col_dims", 1)
                yn = op.attr("y_num_col_dims", 1)
                m = 1
                for v in xs[:xn]:
                    m *= v
                k = 1
                for v in xs[xn:]:
                    k *= v
                n = 1
                for v in ys[yn:]:
                    n *= v
                dt = run_dtype(block, op.input("X")[0])
                if supports_matmul((m, k), (k, n), dt):
                    add("matmul", {"m": m, "k": k, "n": n, "dtype": dt})
    return out


def _gen_artifact_populations(dirname):
    """The paged-attention population a generative artifact's SERVING
    deployment would dispatch on: one key per pool geometry, built from
    the artifact's transformer config plus the serve flags
    (``serve_max_running`` / ``serve_page_tokens``) — the exact
    ``population_key`` the engine consults at construction, so a winner
    tuned here is the winner the engine re-hits. Raises ValueError when
    the artifact's config JSON is unreadable."""
    from paddle_tpu.flags import FLAGS
    from paddle_tpu.inference import GEN_CONFIG_FILE
    from paddle_tpu.kernels.paged_attention import population_key
    from paddle_tpu.serving.kvcache import pages_for
    try:
        with open(os.path.join(dirname, GEN_CONFIG_FILE)) as f:
            cfg = json.load(f)["config"]
        hidden, heads = int(cfg["hidden"]), int(cfg["num_heads"])
        max_seq = int(cfg["max_seq"])
    except Exception as e:
        raise ValueError("generative artifact %r: %s unreadable (%s: %s)"
                         % (dirname, GEN_CONFIG_FILE,
                            type(e).__name__, e)) from e
    page_tokens = int(FLAGS.serve_page_tokens)
    key = population_key(FLAGS.serve_max_running,
                         pages_for(max_seq, page_tokens),
                         page_tokens, heads, hidden // max(heads, 1))
    return [("paged_attention", key)]


def cmd_tune(args):
    """Autotune the Pallas kernels a train config's program actually
    uses (paddle_tpu.tune): enumerate each kernel's valid configs for
    the shapes in the program, compile+parity-check+time every
    candidate, persist winners in the per-(device, shape) cache, and
    print the winners table. ``--dry-run`` only enumerates. Exit 0 on
    success, 1 when a population ends with zero eligible candidates,
    2 when the config fails to build.

    ``config`` may also be a generative-artifact DIRECTORY (an
    ``export_generative`` output): the population is then the
    paged-attention decode key for the deployment geometry the serve
    flags describe, and the cached winner is exactly what
    ``GenerationEngine`` consults when it compiles its decode step."""
    import paddle_tpu as pt
    from paddle_tpu import tune as tune_mod
    from paddle_tpu.tune import results as results_mod
    from paddle_tpu import inference as _inf

    if os.path.isdir(args.config) and _inf.is_generative_artifact(
            args.config):
        try:
            pops = _gen_artifact_populations(args.config)
        except ValueError as e:
            print("tune: %s" % e, file=sys.stderr)
            return 2
    else:
        main, startup = pt.Program(), pt.Program()
        try:
            cfg_mod = _load_config(args.config)
            with pt.program_guard(main, startup):
                cfg_mod.model()
        except Exception as e:
            print("tune: config %r failed to build: %s: %s"
                  % (args.config, type(e).__name__, e), file=sys.stderr)
            return 2
        pops = _tune_populations(main, args.batch,
                                 compute_dtype=args.dtype or None)
    if not pops:
        print("tune: no tunable kernel populations in %r (conv3x3 / "
              "flash_attention / matmul shapes)" % args.config)
        return 0
    from paddle_tpu.flags import FLAGS
    dev = results_mod.device_kind()
    budget = args.budget if args.budget > 0 else (FLAGS.tune_budget or
                                                  None)
    timer = None
    if args.timer == "wall":
        timer = tune_mod.wall_timer()
    elif args.timer == "model":
        timer = tune_mod.model_timer()
    if args.dry_run:
        # same budget arithmetic as the real loop (stock rung included),
        # so the printed count is exactly what a run would time
        print("%-16s %-44s %10s" % ("kernel", "signature", "candidates"))
        for kernel, key in pops:
            space = tune_mod.get_space(kernel)
            cands = space.candidates(
                key, budget=(budget - 1) if budget else None)
            print("%-16s %-44s %10d"
                  % (kernel, tune_mod.signature(key), len(cands) + 1))
        print("tune: dry run — nothing timed, nothing cached")
        return 0
    from paddle_tpu import profiler as _prof
    rows, failed = [], 0
    cache = tune_mod.WinnerCache()
    print("%-16s %-44s %-34s %12s %6s" % ("kernel", "signature", "winner",
                                          "time", "cands"))
    for kernel, key in pops:
        res = tune_mod.autotune(kernel, key, timer=timer, budget=budget,
                                cache=cache)
        _prof.update_tune_counters(tune_loops=1,
                                   tune_candidates=len(res.records))
        rows.append(res.row())
        if not res.ok:
            failed += 1
            print("%-16s %-44s %-34s %12s %6d"
                  % (kernel, res.sig, "<NO ELIGIBLE CANDIDATE>", "-",
                     len(res.records)))
            continue
        win = ("xla" if res.winner.get("use") == "xla" else
               ",".join("%s=%s" % kv for kv in sorted(res.winner.items())))
        print("%-16s %-44s %-34s %10.3fms %6d"
              % (kernel, res.sig, win, res.winner_seconds * 1e3,
                 len(res.records)))
    rec = results_mod.bench_record(
        "tune", rows, device=dev,
        meta={"config": args.config, "batch": args.batch,
              "budget": budget or 0,
              "timer": rows and rows[0]["timer"] or None,
              "cache_dir": cache.cache_dir})
    path = results_mod.write_result(rec, path=args.out)
    print("tune: %d population(s), %d failed; winners cached in %s; "
          "evidence %s" % (len(pops), failed, cache.path, path))
    return 1 if failed else 0


def cmd_info(args):
    import jax

    import paddle_tpu as pt
    devs = jax.devices()
    print(json.dumps({
        "version": pt.__version__,
        "platform": devs[0].platform,
        "device_count": len(devs),
        "devices": [str(d) for d in devs],
        "registered_ops": len(pt.ops.registered_ops()),
        "native_runtime": pt.native.available(),
    }, indent=2))
    return 0


def cmd_convert(args):
    import paddle_tpu as pt

    mod = pt.dataset
    for part in args.dataset.split("."):
        mod = getattr(mod, part)
    reader = getattr(mod, args.split)()
    paths = pt.dataset.common.convert(args.output, reader,
                                      args.records_per_shard, args.dataset)
    print(json.dumps({"shards": paths}))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="paddle_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train a model config")
    t.add_argument("config")
    t.add_argument("--num_passes", type=int, default=0)
    t.add_argument("--learning_rate", type=float, default=0.01)
    t.add_argument("--checkpoint_dir", default="")
    t.add_argument("--log_period", type=int, default=10)
    t.set_defaults(fn=cmd_train)

    b = sub.add_parser("bench", help="run a benchmark suite")
    b.add_argument("suite", choices=["resnet", "image", "rnn"])
    b.add_argument("--model", default=None)
    b.add_argument("--batch_size", type=int, default=64)
    b.add_argument("extra", nargs="*")
    b.set_defaults(fn=cmd_bench)

    lint = sub.add_parser(
        "lint", help="statically verify a train config's Program IR "
                     "(paddle_tpu.analysis; exit 1 on PT errors)")
    lint.add_argument("config")
    lint.add_argument("--dot", default=None, metavar="PATH",
                      help="write a graphviz .dot of the main block with "
                           "failing ops highlighted")
    lint.add_argument("--strict", action="store_true",
                      help="treat warnings as failures")
    lint.add_argument("--comm", action="store_true",
                      help="run the collective-consistency pass "
                           "(PT020-PT023) over the config's parameter "
                           "grads template: bucket plan coverage, "
                           "canonical issue order, (host, chip) "
                           "axis-group factorisation, overlap schedule "
                           "vs gradient finalisation")
    lint.add_argument("--comm-axis", type=int, default=8,
                      dest="comm_axis",
                      help="data-axis size (replica count) the comm "
                           "pass checks against")
    lint.add_argument("--comm-policy", default="", dest="comm_policy",
                      help="comm policy base for the pass (empty = "
                           "FLAGS.comm_policy)")
    lint.add_argument("--comm-hosts", type=int, default=0,
                      dest="comm_hosts",
                      help="host count for the hierarchical/multipath "
                           "factorisation (0 = FLAGS.comm_hosts)")
    lint.add_argument("--memory", action="store_true",
                      help="run the static memory planner (PT030-PT033, "
                           "analysis.memory): liveness-based per-device "
                           "peak-HBM prediction over the full train step "
                           "(backward + optimizer appended when the "
                           "config names one), checked against the "
                           "budget; prints the residency table")
    lint.add_argument("--budget-gb", type=float, default=0.0,
                      dest="budget_gb",
                      help="per-device HBM budget for --memory (GiB; "
                           "0 = FLAGS.memory_budget_gb, which at 0 "
                           "leaves PT030 unchecked — the honest default "
                           "on a devbox with no TPU attached)")
    lint.add_argument("--batch", type=int, default=16,
                      help="global batch substituted for the feed "
                           "wildcard dim (-1) in the --memory pass")
    lint.add_argument("--mesh", default="dp=1",
                      help="mesh for the --memory/--sharding passes, "
                           "e.g. 'dp=8' or 'dp=4,fsdp=2,tp=2': the "
                           "batch shards over dp; params replicate "
                           "unless --sharding propagates their specs")
    lint.add_argument("--sharding", action="store_true",
                      help="run the static sharding analyzer "
                           "(PT040-PT045, analysis.sharding): propagate "
                           "PartitionSpecs through the train step over "
                           "--mesh, price implicit reshards, and audit "
                           "the sharded collective vocabulary; prints "
                           "the sharding plan table")
    lint.add_argument("--spec", action="append", default=None,
                      metavar="VAR=SPEC",
                      help="override/seed one variable's PartitionSpec "
                           "for --sharding (repeatable), e.g. "
                           "--spec 'x=dp,tp' --spec 'w=fsdp+tp,-' "
                           "(',' separates dims, '+' joins axes on one "
                           "dim, '-' or empty = replicated dim)")
    lint.add_argument("--all", action="store_true",
                      help="run every pass (structural + --comm + "
                           "--memory + --sharding) with one combined "
                           "summary and exit code")
    lint.set_defaults(fn=cmd_lint)

    sv = sub.add_parser(
        "serve", help="serve a compiled or generative artifact over "
                      "HTTP (paddle_tpu.serving; generative artifacts "
                      "get continuous-batching :generate; SIGTERM "
                      "drains and exits 0)")
    sv.add_argument("artifact_dir",
                    help="directory written by inference.export_compiled "
                         "or inference.export_generative")
    sv.add_argument("--name", default="default",
                    help="model name in the registry / URL")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8500,
                    help="0 binds a free port (printed on the readiness "
                         "line)")
    sv.add_argument("--max_batch", type=int, default=0,
                    help="override FLAGS.serve_max_batch (0 = flag)")
    sv.add_argument("--batch_timeout_ms", type=float, default=-1.0,
                    help="override FLAGS.serve_batch_timeout_ms "
                         "(negative = flag)")
    sv.add_argument("--queue_depth", type=int, default=0,
                    help="override FLAGS.serve_queue_depth (0 = flag)")
    sv.add_argument("--max_running", type=int, default=0,
                    help="generative artifacts: override "
                         "FLAGS.serve_max_running (0 = flag)")
    sv.add_argument("--kv_pages", type=int, default=0,
                    help="generative artifacts: override "
                         "FLAGS.serve_kv_pages (0 = flag)")
    sv.add_argument("--page_tokens", type=int, default=0,
                    help="generative artifacts: override "
                         "FLAGS.serve_page_tokens (0 = flag)")
    sv.add_argument("--draft_dir", default="",
                    help="generative artifacts: pair a draft model "
                         "(an export_generative directory, same "
                         "vocabulary) for speculative decoding; empty "
                         "defers to FLAGS.serve_draft_dir / a paired "
                         "speculative artifact's own draft")
    sv.add_argument("--spec_k", type=int, default=0,
                    help="generative artifacts: speculation depth "
                         "override (0 = FLAGS.serve_spec_k or the "
                         "paired artifact's qualified k)")
    sv.add_argument("--prefix_sharing", "--prefix-sharing",
                    action="store_true",
                    help="generative artifacts: copy-on-write prefix "
                         "sharing over the paged KV pool — concurrent "
                         "same-prefix requests pin one physical copy "
                         "of their shared prefill pages (greedy output "
                         "stays bit-identical; default "
                         "FLAGS.serve_prefix_sharing)")
    sv.add_argument("--tier", default="", choices=["", "prefill",
                                                   "decode"],
                    help="serving class for a disaggregated fleet "
                         "(advertised through /statz so the router "
                         "two-hops :generate as prefill -> handoff -> "
                         "decode); empty = a do-everything replica")
    sv.add_argument("--extra_model", action="append", default=[],
                    metavar="NAME=DIR",
                    help="additional artifact(s) to publish from the "
                         "same process (repeatable): a router replica "
                         "serves its predict and generate models side "
                         "by side this way")
    sv.set_defaults(fn=cmd_serve)

    rt = sub.add_parser(
        "route", help="front N supervised `serve` replicas with the "
                      "multi-replica router (paddle_tpu.serving.router: "
                      "least-loaded proxying, health eject/probation, "
                      "failover, rolling :reload; SIGTERM drains the "
                      "fleet and exits 0)")
    rt.add_argument("artifact_dir",
                    help="artifact every replica serves (compiled or "
                         "generative; see also --extra_model)")
    rt.add_argument("--name", default="default",
                    help="model name in the registry / URL")
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument("--port", type=int, default=8600,
                    help="router port; 0 binds a free one (printed on "
                         "the readiness line). Replicas always bind "
                         "free ports")
    rt.add_argument("--replicas", type=int, default=0,
                    help="worker process count (0 = "
                         "FLAGS.route_replicas)")
    rt.add_argument("--policy", choices=["least_loaded", "round_robin"],
                    default="least_loaded",
                    help="replica selection: least_loaded scores each "
                         "replica from its polled /statz (queue depth + "
                         "generation backlog + KV pressure) plus live "
                         "in-flight counts; round_robin is the "
                         "load-blind baseline benchmark/load_bench.py "
                         "compares against")
    rt.add_argument("--poll_ms", type=int, default=0,
                    help="health/load poll interval (0 = "
                         "FLAGS.route_poll_ms)")
    rt.add_argument("--restart_budget", type=int, default=-1,
                    help="restarts per dead replica before declaring it "
                         "lost (negative = FLAGS.route_restart_budget)")
    rt.add_argument("--autoscale", action="store_true",
                    help="close the loop on the pressure signal "
                         "(paddle_tpu.serving.autoscale): grow/shrink "
                         "the fleet between --min_replicas and "
                         "--max_replicas from the EWMA-smoothed "
                         "per-model pressure in /statz — scale-up "
                         "after a sustained overload, drain-first "
                         "scale-down after a longer quiet window, "
                         "crash-loop circuit breaker on dying "
                         "scale-ups")
    rt.add_argument("--min_replicas", "--min-replicas", type=int,
                    default=1,
                    help="autoscale floor (also the initial fleet size "
                         "when --autoscale is on and --replicas is 0)")
    rt.add_argument("--max_replicas", "--max-replicas", type=int,
                    default=0,
                    help="autoscale ceiling (0 = max(min_replicas, "
                         "FLAGS.route_replicas))")
    rt.add_argument("--scale_up_pressure", "--scale-up-pressure",
                    type=float, default=0.0,
                    help="smoothed pressure that triggers a scale-up "
                         "after k_up consecutive control ticks (0 = "
                         "FLAGS.route_scale_up_pressure)")
    rt.add_argument("--scale_down_pressure", "--scale-down-pressure",
                    type=float, default=-1.0,
                    help="smoothed pressure under which the (longer) "
                         "quiet window triggers a drain-first "
                         "scale-down (negative = "
                         "FLAGS.route_scale_down_pressure)")
    rt.add_argument("--cooldown_s", "--cooldown-s", type=float,
                    default=-1.0,
                    help="minimum seconds between scale-ups; the "
                         "scale-down cooldown is 2x (negative = "
                         "FLAGS.route_cooldown_s)")
    rt.add_argument("--grace_sec", type=float, default=5.0,
                    help="SIGTERM drain window before the pool "
                         "escalates to SIGKILL at shutdown")
    rt.add_argument("--max_batch", type=int, default=0,
                    help="forwarded to every replica (0 = flag)")
    rt.add_argument("--batch_timeout_ms", type=float, default=-1.0,
                    help="forwarded to every replica (negative = flag)")
    rt.add_argument("--queue_depth", type=int, default=0,
                    help="forwarded to every replica (0 = flag)")
    rt.add_argument("--max_running", type=int, default=0,
                    help="forwarded to every replica (0 = flag)")
    rt.add_argument("--kv_pages", type=int, default=0,
                    help="forwarded to every replica (0 = flag)")
    rt.add_argument("--page_tokens", type=int, default=0,
                    help="forwarded to every replica (0 = flag)")
    rt.add_argument("--draft_dir", default="",
                    help="speculation draft forwarded to every replica "
                         "(empty = none)")
    rt.add_argument("--spec_k", type=int, default=0,
                    help="speculation depth forwarded to every replica "
                         "(0 = flag/artifact default)")
    rt.add_argument("--prefix_sharing", "--prefix-sharing",
                    action="store_true",
                    help="forward copy-on-write KV prefix sharing to "
                         "every replica")
    rt.add_argument("--tiers", default="",
                    help="disaggregated fleet layout, e.g. "
                         "'prefill=1,decode=2': the first N replicas "
                         "serve --tier prefill, the rest --tier decode, "
                         "and the router two-hops :generate as "
                         "prefill -> handoff -> decode (fault site "
                         "serving.ship: a failed hop re-prefills on "
                         "the decode tier). With --autoscale each tier "
                         "gets its OWN controller on its class-correct "
                         "signal (queue depth / page occupancy), "
                         "floored at its configured count")
    rt.add_argument("--extra_model", action="append", default=[],
                    metavar="NAME=DIR",
                    help="additional artifact(s) every replica publishes "
                         "(repeatable)")
    rt.add_argument("--state-dir", "--state_dir", default=None,
                    dest="state_dir",
                    help="durable event directory (events.jsonl): "
                         "ejections, failovers, breaker transitions, "
                         "autoscale decisions and gray-failure verdicts "
                         "survive a router crash — the serving twin of "
                         "launch --state-dir")
    rt.set_defaults(fn=cmd_route)

    acc = sub.add_parser(
        "accounting", help="per-chip collective bytes + comm-policy "
                           "matrix for a train config (paddle_tpu.comm; "
                           "pure analysis, no devices)")
    acc.add_argument("config")
    acc.add_argument("--mesh", default="dp=8",
                     help="mesh axis sizes, e.g. 'dp=8' or 'dp=4,tp=2'")
    acc.add_argument("--hosts", type=int, default=0,
                     help="host count for the hierarchical rows "
                          "(0 = 2 when the axis divides, else flat)")
    acc.add_argument("--bucket_mb", type=float, default=0.0,
                     help="override FLAGS.comm_bucket_mb (0 = flag)")
    acc.add_argument("--batch", type=int, default=16,
                     help="global batch for the memory columns (shards "
                          "over the data axis; feeds' wildcard dim)")
    acc.add_argument("--split-ratio", type=float, default=-1.0,
                     dest="split_ratio",
                     help="primary-path fraction for the multipath rows "
                          "(negative = FLAGS.comm_split_ratio; derive "
                          "from measured bandwidths via "
                          "comm.measured_split_ratio)")
    acc.add_argument("--generative", default="", metavar="DIR",
                     help="also price a generative artifact's KV-pool "
                          "residency (inference.generative_residency): "
                          "physical pages/bytes + the dedup-ratio "
                          "capacity columns as a 'kv_pool' section; a "
                          "speculative pairing folds the draft in")
    acc.add_argument("--dedup-ratio", type=float, default=1.0,
                     dest="dedup_ratio",
                     help="prefix-sharing dedup ratio to price the "
                          "--generative capacity columns at (1.0 = no "
                          "sharing; e.g. the live pool's observed "
                          "dedup_ratio stat)")
    acc.add_argument("--sharding", action="store_true",
                     help="add the propagated-PartitionSpec plan "
                          "(analysis.sharding PT040-PT045): per-class "
                          "spec table, fingerprint, priced implicit "
                          "reshards, diagnostics")
    acc.set_defaults(fn=cmd_accounting)

    tn = sub.add_parser(
        "tune", help="autotune the Pallas kernels a train config uses "
                     "(paddle_tpu.tune; winners persist per device+shape)")
    tn.add_argument("config",
                    help="train config .py, or a generative-artifact "
                         "directory (export_generative output) — the "
                         "latter tunes the paged-attention decode key "
                         "for the serve-flag pool geometry")
    tn.add_argument("--batch", type=int, default=8,
                    help="batch size substituted for the feed dim (-1) "
                         "when deriving kernel shapes")
    tn.add_argument("--dtype", default=None,
                    help="compute dtype for the conv/matmul keys (e.g. "
                         "bfloat16). Default: bfloat16 when the config "
                         "builds an AMP-marked program — dispatch keys "
                         "on the dtype the op RUNS at — else the "
                         "declared var dtype")
    tn.add_argument("--budget", type=int, default=0,
                    help="cap candidates per (kernel, shape), stock-XLA "
                         "rung included (0 = FLAGS.tune_budget)")
    tn.add_argument("--dry-run", action="store_true",
                    help="enumerate populations and candidate counts "
                         "only; nothing timed or cached")
    tn.add_argument("--timer", choices=["auto", "wall", "model"],
                    default="auto",
                    help="auto = wall clock on tpu/axon, deterministic "
                         "model timer elsewhere (CPU interpret-mode wall "
                         "times are noise)")
    tn.add_argument("--out", default=None, metavar="PATH",
                    help="evidence-record path (default "
                         "benchmark/results/tune_<device>.json)")
    tn.set_defaults(fn=cmd_tune)

    i = sub.add_parser("info", help="device / build report")
    i.set_defaults(fn=cmd_info)

    c = sub.add_parser("convert", help="dataset -> recordio shards")
    c.add_argument("dataset")
    c.add_argument("--split", default="train")
    c.add_argument("--output", default="./recordio")
    c.add_argument("--records_per_shard", type=int, default=4096)
    c.set_defaults(fn=cmd_convert)

    # the reference exposed cluster fan-out through the same binary
    # (`paddle train/pserver`, scripts/cluster_train); mirror that shape
    from .launch import add_launch_arguments
    ln = sub.add_parser(
        "launch", help="multi-process launcher — fail-fast or "
                       "--elastic survive-and-resize (see "
                       "paddle_tpu.launch / paddle_tpu.elastic)")
    add_launch_arguments(ln)
    ln.add_argument("script_argv", nargs=argparse.REMAINDER)

    def cmd_launch(args):
        from .launch import _shell_rc, run_from_args
        if not args.script_argv:
            p.error("launch: missing training script")
        return _shell_rc(run_from_args(args, args.script_argv))

    ln.set_defaults(fn=cmd_launch)

    args = p.parse_args(argv)
    return args.fn(args)
