"""Multi-process / multi-host launcher: ``python -m paddle_tpu.launch``.

reference: paddle/scripts/cluster_train/paddle.py (the v1 cluster launcher:
fans a job out over conf.py's HOSTS, wires trainer_id/ports, aborts the job
when any worker dies) and the fluid k8s yamls (benchmark/cluster/vgg16/*).

TPU-native shape: every host runs ONE process (jax.distributed handles the
in-host chips); the launcher assigns ranks, points everyone at the
coordinator, and propagates failure — the moral equivalent of the
reference's ssh fan-out, for localhost process counts or as the per-host
entry point under k8s (see cluster/ for pod specs).

Usage:
  python -m paddle_tpu.launch --nprocs 4 --coordinator HOST:PORT \
      train.py --your-args
Workers see PADDLE_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID, which
``paddle_tpu.parallel.env.init_distributed()`` consumes.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def launch(nprocs, coordinator, script_argv, env=None, python=None):
    """Spawn ``nprocs`` ranked worker processes; return the first non-zero
    exit code (killing the rest), or 0. The fail-fast barrier matches the
    reference launcher's job-abort semantics."""
    procs = []
    base_env = dict(env if env is not None else os.environ)
    python = python or sys.executable
    rc = 0
    try:
        for rank in range(nprocs):
            e = dict(base_env)
            e["PADDLE_TPU_COORDINATOR"] = coordinator
            e["PADDLE_TPU_NUM_PROCESSES"] = str(nprocs)
            e["PADDLE_TPU_PROCESS_ID"] = str(rank)
            procs.append(subprocess.Popen([python] + list(script_argv),
                                          env=e))
        remaining = set(range(nprocs))
        while remaining and rc == 0:
            for i in list(remaining):
                r = procs[i].poll()
                if r is None:
                    continue
                remaining.discard(i)
                if r != 0:
                    rc = r
            if remaining and rc == 0:
                import time
                time.sleep(0.05)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.launch",
        description="rank-assigning multi-process launcher")
    ap.add_argument("--nprocs", type=int, default=1)
    ap.add_argument("--coordinator", default="127.0.0.1:12355")
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="script and its args")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("missing training script")
    return launch(args.nprocs, args.coordinator, args.script)


if __name__ == "__main__":
    sys.exit(main())
