"""Multi-process / multi-host launcher: ``python -m paddle_tpu.launch``.

reference: paddle/scripts/cluster_train/paddle.py (the v1 cluster launcher:
fans a job out over conf.py's HOSTS, wires trainer_id/ports, aborts the job
when any worker dies) and the fluid k8s yamls (benchmark/cluster/vgg16/*).

TPU-native shape: every host runs ONE process (jax.distributed handles the
in-host chips); the launcher assigns ranks, points everyone at the
coordinator, and propagates failure — the moral equivalent of the
reference's ssh fan-out, for localhost process counts or as the per-host
entry point under k8s (see cluster/ for pod specs).

Two supervision modes:

- **fail-fast** (default): any worker's non-zero exit kills the job —
  the reference launcher's job-abort semantics. Exits are waited
  event-driven (no busy-poll); shutdown SIGTERMs the survivors and
  escalates to SIGKILL after ``--grace-sec`` so a hung worker cannot
  wedge the launcher; the first failing worker's REAL exit code
  propagates (signal deaths map to the shell convention 128+N).
- **elastic** (``--elastic`` / ``FLAGS.elastic``): worker death is
  classified and survived — transient failures restart the gang at
  full world size on a bounded RetryPolicy backoff budget; permanent
  losses (signal deaths, exhausted budget) shrink the world to the
  survivors, re-queue the dead worker's leased dataset tasks through
  the task master, and relaunch from ``load_latest`` + the paired
  master snapshot, recording an ``elastic_resize`` event. The job only
  dies when the quorum (``--elastic-min-workers``) is gone. See
  :mod:`paddle_tpu.elastic`.

Usage:
  python -m paddle_tpu.launch --nprocs 4 --coordinator HOST:PORT \
      [--elastic --state-dir DIR --snapshot-root CKPT_ROOT] \
      train.py --your-args
Workers see PADDLE_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID (plus
PADDLE_TPU_ELASTIC / _ELASTIC_GENERATION / _MASTER_ADDR under
``--elastic``), which ``paddle_tpu.parallel.env`` consumes and
validates.
"""
from __future__ import annotations

import argparse
import os
import sys


def launch(nprocs, coordinator, script_argv, env=None, python=None,
           grace_sec=10.0, master_tasks=None, master_timeout_sec=60.0):
    """Fail-fast mode: spawn ``nprocs`` ranked worker processes; return
    the first non-zero exit code (stopping the rest: SIGTERM, then
    SIGKILL after ``grace_sec``), or 0. ``master_tasks`` optionally
    hosts a launcher-owned task master (payload list) the workers find
    at ``PADDLE_TPU_MASTER_ADDR`` — the single-generation counterpart
    of the elastic supervisor's, so fail-fast and elastic runs of the
    same script are comparable."""
    from .elastic.supervisor import Gang, TaskMasterHost

    base_env = dict(env if env is not None else os.environ)
    master = None
    if master_tasks is not None:
        master = TaskMasterHost(master_tasks,
                                timeout_sec=master_timeout_sec)
    try:
        envs = []
        for rank in range(nprocs):
            e = dict(base_env)
            e["PADDLE_TPU_COORDINATOR"] = coordinator
            e["PADDLE_TPU_NUM_PROCESSES"] = str(nprocs)
            e["PADDLE_TPU_PROCESS_ID"] = str(rank)
            # drain budget for the trainers' SIGTERM preemption hook
            e["PADDLE_TPU_GRACE_SEC"] = str(grace_sec)
            if master is not None:
                e["PADDLE_TPU_MASTER_ADDR"] = master.addr
                e["PADDLE_TPU_MASTER_TIMEOUT"] = str(master_timeout_sec)
            envs.append(e)
        gang = Gang(script_argv, envs, python=python)
        try:
            rc, done = 0, set()
            # event-driven: each exit arrives on the gang's queue;
            # nothing polls (the old 50ms busy-loop is gone)
            while len(done) < nprocs:
                rank, r = gang.next_exit()
                if r != 0:
                    rc = r
                    break
                done.add(rank)
            return rc
        finally:
            # every exit path — including an exception in the wait
            # loop — drains the gang; no orphan workers
            gang.stop(grace_sec)
    finally:
        if master is not None:
            master.close()


def launch_elastic(nprocs, coordinator, script_argv, env=None, python=None,
                   grace_sec=10.0, min_workers=None, restart_budget=None,
                   state_dir=None, master_tasks=None,
                   master_timeout_sec=60.0, snapshot_root=None,
                   gray_ratio=None, gray_budget=None):
    """Elastic mode: survive-and-resize supervision (see
    :class:`paddle_tpu.elastic.ElasticSupervisor` for the full
    contract). Returns the job's exit code: 0 when a generation
    completes, the real failing code when the quorum is lost.
    ``gray_ratio``/``gray_budget`` arm gray-failure detection over the
    workers' step-time heartbeats (FLAGS.gray_step_ratio /
    FLAGS.gray_mitigation_budget when None)."""
    from .elastic.supervisor import ElasticSupervisor

    return ElasticSupervisor(
        nprocs, coordinator, script_argv, min_workers=min_workers,
        restart_budget=restart_budget, grace_sec=grace_sec, env=env,
        python=python, state_dir=state_dir, master_tasks=master_tasks,
        master_timeout_sec=master_timeout_sec,
        snapshot_root=snapshot_root, gray_ratio=gray_ratio,
        gray_budget=gray_budget).run()


def _shell_rc(rc):
    """Popen returncodes are negative for signal deaths; shells expect
    128+N. The REAL code still propagates either way."""
    return rc if rc >= 0 else 128 - rc


def add_launch_arguments(ap):
    """The launcher's argument set, shared with the ``paddle_tpu
    launch`` CLI verb (cli.py)."""
    from .flags import FLAGS
    ap.add_argument("--nprocs", type=int, default=1)
    ap.add_argument("--coordinator", default="127.0.0.1:12355")
    ap.add_argument("--grace-sec", type=float, default=10.0,
                    dest="grace_sec",
                    help="SIGTERM drain window before SIGKILL when "
                         "stopping workers (a hung worker cannot wedge "
                         "the launcher)")
    ap.add_argument("--elastic", action=argparse.BooleanOptionalAction,
                    default=FLAGS.elastic,
                    help="survive-and-resize supervision instead of "
                         "fail-fast job abort (paddle_tpu.elastic); "
                         "--no-elastic forces fail-fast even when the "
                         "elastic flag defaults it on")
    ap.add_argument("--elastic-min-workers", type=int,
                    default=FLAGS.elastic_min_workers,
                    dest="elastic_min_workers",
                    help="quorum: smallest world size a resize may "
                         "reach; below it the job aborts with the real "
                         "exit code")
    ap.add_argument("--elastic-restart-budget", type=int,
                    default=FLAGS.elastic_restart_budget,
                    dest="elastic_restart_budget",
                    help="transient failures restarted at FULL world "
                         "size (RetryPolicy backoff) before the next "
                         "one counts as permanent")
    ap.add_argument("--state-dir", default=None, dest="state_dir",
                    help="elastic audit-trail directory (events.jsonl "
                         "+ per-generation worker pid maps)")
    ap.add_argument("--snapshot-root", default=None, dest="snapshot_root",
                    help="checkpoint retention root; a resize restores "
                         "the task master from the snapshot PAIRED "
                         "with the checkpoint the survivors resume "
                         "from (paddle_tpu.elastic.resume)")
    ap.add_argument("--gray-step-ratio", type=float,
                    default=FLAGS.gray_step_ratio,
                    dest="gray_step_ratio",
                    help="gray-failure detection: condemn a rank whose "
                         "step-time EWMA sits this factor above the "
                         "gang median (resilience.grayfail; 0 = off)")
    ap.add_argument("--gray-mitigation-budget", type=int,
                    default=FLAGS.gray_mitigation_budget,
                    dest="gray_mitigation_budget",
                    help="transient full-world restarts spent on a "
                         "gray-slow rank before it is demoted to "
                         "permanent (resize); job-scoped")
    ap.add_argument("--master-tasks-file", default=None,
                    dest="master_tasks_file",
                    help="newline-separated task payloads; hosts a "
                         "launcher-owned task master the workers find "
                         "at PADDLE_TPU_MASTER_ADDR")
    ap.add_argument("--master-timeout-sec", type=float, default=60.0,
                    dest="master_timeout_sec",
                    help="task-master lease TTL (doubles as the worker "
                         "registry heartbeat lease)")
    return ap


def run_from_args(args, script_argv):
    """Dispatch a parsed launcher namespace (shared with cli.py)."""
    master_tasks = None
    if args.master_tasks_file:
        with open(args.master_tasks_file, "rb") as f:
            master_tasks = [ln for ln in f.read().splitlines() if ln]
    if args.elastic:
        return launch_elastic(
            args.nprocs, args.coordinator, script_argv,
            grace_sec=args.grace_sec,
            min_workers=args.elastic_min_workers,
            restart_budget=args.elastic_restart_budget,
            state_dir=args.state_dir, master_tasks=master_tasks,
            master_timeout_sec=args.master_timeout_sec,
            snapshot_root=args.snapshot_root,
            gray_ratio=args.gray_step_ratio,
            gray_budget=args.gray_mitigation_budget)
    return launch(args.nprocs, args.coordinator, script_argv,
                  grace_sec=args.grace_sec, master_tasks=master_tasks,
                  master_timeout_sec=args.master_timeout_sec)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.launch",
        description="rank-assigning multi-process launcher "
                    "(fail-fast or --elastic survive-and-resize)")
    add_launch_arguments(ap)
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="script and its args")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("missing training script")
    return _shell_rc(run_from_args(args, args.script))


if __name__ == "__main__":
    sys.exit(main())
