"""VGG-16.

reference: benchmark/paddle/image/vgg.py and
python/paddle/fluid/tests/book/test_image_classification.py (vgg16_bn_drop),
benchmark/cluster/vgg16/vgg16_fluid.py.
"""
from __future__ import annotations

from .. import layers

__all__ = ["vgg16", "vgg_cifar"]


def _conv_block(input, num_filter, groups, dropouts=None, is_test=False,
                with_bn=True):
    """conv(3x3,relu) x groups -> max-pool 2x2; optional per-conv dropout + BN
    (the book's img_conv_group equivalent)."""
    tmp = input
    for i in range(groups):
        if with_bn:
            tmp = layers.conv2d(tmp, num_filters=num_filter, filter_size=3,
                                stride=1, padding=1, act=None,
                                bias_attr=False)
            tmp = layers.batch_norm(tmp, act="relu", is_test=is_test)
        else:
            tmp = layers.conv2d(tmp, num_filters=num_filter, filter_size=3,
                                stride=1, padding=1, act="relu")
        if dropouts and dropouts[i]:
            tmp = layers.dropout(tmp, dropout_prob=dropouts[i],
                                 is_test=is_test)
    return layers.pool2d(tmp, pool_size=2, pool_stride=2, pool_type="max")


def vgg16(input, class_dim=1000, is_test=False, with_bn=True):
    """Full VGG-16, BN variant by default (the bench config).
    reference: benchmark/paddle/image/vgg.py."""
    c1 = _conv_block(input, 64, 2, is_test=is_test, with_bn=with_bn)
    c2 = _conv_block(c1, 128, 2, is_test=is_test, with_bn=with_bn)
    c3 = _conv_block(c2, 256, 3, is_test=is_test, with_bn=with_bn)
    c4 = _conv_block(c3, 512, 3, is_test=is_test, with_bn=with_bn)
    c5 = _conv_block(c4, 512, 3, is_test=is_test, with_bn=with_bn)
    d1 = layers.dropout(c5, dropout_prob=0.5, is_test=is_test)
    if with_bn:
        fc1 = layers.fc(d1, size=4096, act=None)
        fc1 = layers.batch_norm(fc1, act="relu", is_test=is_test,
                                data_layout="NHWC")
    else:
        fc1 = layers.fc(d1, size=4096, act="relu")
    d2 = layers.dropout(fc1, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(d2, size=4096, act="relu")
    return layers.fc(fc2, size=class_dim, act="softmax")


def vgg_cifar(input, class_dim=10, is_test=False):
    """The book's cifar VGG (vgg16_bn_drop with per-conv dropouts).
    reference: python/paddle/fluid/tests/book/test_image_classification.py."""
    c1 = _conv_block(input, 64, 2, dropouts=[0.3, 0], is_test=is_test)
    c2 = _conv_block(c1, 128, 2, dropouts=[0.4, 0], is_test=is_test)
    c3 = _conv_block(c2, 256, 3, dropouts=[0.4, 0.4, 0], is_test=is_test)
    c4 = _conv_block(c3, 512, 3, dropouts=[0.4, 0.4, 0], is_test=is_test)
    c5 = _conv_block(c4, 512, 3, dropouts=[0.4, 0.4, 0], is_test=is_test)
    d1 = layers.dropout(c5, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(d1, size=512, act=None)
    bn = layers.batch_norm(fc1, act="relu", is_test=is_test,
                           data_layout="NHWC")
    d2 = layers.dropout(bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(d2, size=512, act=None)
    return layers.fc(fc2, size=class_dim, act="softmax")
