"""ResNet family.

reference: benchmark/paddle/image/resnet.py (ImageNet ResNet-50/101/152 with
bottleneck blocks) and python/paddle/fluid/tests/book/test_image_classification.py
(cifar ResNet, basic blocks, depth 32).

TPU notes: NCHW layout kept for API parity (XLA relayouts for the MXU
internally); batch_norm folded per conv; all matarith stays bf16-friendly —
the executor casts under a bf16 policy without model changes.
"""
from __future__ import annotations

from .. import layers

__all__ = ["resnet", "resnet_cifar10", "resnet_imagenet"]


def _conv_bn(input, ch_out, filter_size, stride, padding, act="relu",
             is_test=False):
    conv = layers.conv2d(input, num_filters=ch_out, filter_size=filter_size,
                         stride=stride, padding=padding, act=None,
                         bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _shortcut(input, ch_in, ch_out, stride, is_test=False):
    if ch_in != ch_out or stride != 1:
        return _conv_bn(input, ch_out, 1, stride, 0, act=None,
                        is_test=is_test)
    return input


def _basicblock(input, ch_in, ch_out, stride, is_test=False):
    """2x3x3 residual block (cifar / resnet-18/34).
    reference: benchmark/paddle/image/resnet.py (basicblock)."""
    short = _shortcut(input, ch_in, ch_out, stride, is_test=is_test)
    conv1 = _conv_bn(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = _conv_bn(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test)
    return layers.elementwise_add(short, conv2, act="relu")


def _bottleneck(input, ch_in, ch_out, stride, is_test=False):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (resnet-50+).
    reference: benchmark/paddle/image/resnet.py (bottleneck)."""
    short = _shortcut(input, ch_in, ch_out * 4, stride, is_test=is_test)
    conv1 = _conv_bn(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = _conv_bn(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = _conv_bn(conv2, ch_out * 4, 1, 1, 0, act=None, is_test=is_test)
    return layers.elementwise_add(short, conv3, act="relu")


def _layer_warp(block_func, input, ch_in, ch_out, count, stride,
                is_test=False):
    res = block_func(input, ch_in, ch_out, stride, is_test=is_test)
    ch_in = ch_out * (4 if block_func is _bottleneck else 1)
    for _ in range(1, count):
        res = block_func(res, ch_in, ch_out, 1, is_test=is_test)
    return res


_IMAGENET_CFG = {
    18: (_basicblock, [2, 2, 2, 2]),
    34: (_basicblock, [3, 4, 6, 3]),
    50: (_bottleneck, [3, 4, 6, 3]),
    101: (_bottleneck, [3, 4, 23, 3]),
    152: (_bottleneck, [3, 8, 36, 3]),
}


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    """ImageNet-style ResNet; returns softmax prediction.
    reference: benchmark/paddle/image/resnet.py (resnet_imagenet)."""
    block_func, stages = _IMAGENET_CFG[depth]
    conv1 = _conv_bn(input, 64, 7, 2, 3, is_test=is_test)
    pool1 = layers.pool2d(conv1, pool_size=3, pool_stride=2, pool_padding=1,
                          pool_type="max")
    res = pool1
    ch_in = 64
    for i, (count, ch_out) in enumerate(zip(stages, [64, 128, 256, 512])):
        stride = 1 if i == 0 else 2
        res = _layer_warp(block_func, res, ch_in, ch_out, count, stride,
                          is_test=is_test)
        ch_in = ch_out * (4 if block_func is _bottleneck else 1)
    pool2 = layers.pool2d(res, pool_size=7, pool_stride=1, pool_type="avg",
                          global_pooling=True)
    return layers.fc(pool2, size=class_dim, act="softmax")


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    """Cifar ResNet with (depth-2)/6 basic blocks per stage.
    reference: python/paddle/fluid/tests/book/test_image_classification.py
    (resnet_cifar10)."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = _conv_bn(input, 16, 3, 1, 1, is_test=is_test)
    res1 = _layer_warp(_basicblock, conv1, 16, 16, n, 1, is_test=is_test)
    res2 = _layer_warp(_basicblock, res1, 16, 32, n, 2, is_test=is_test)
    res3 = _layer_warp(_basicblock, res2, 32, 64, n, 2, is_test=is_test)
    pool = layers.pool2d(res3, pool_size=8, pool_stride=1, pool_type="avg",
                         global_pooling=True)
    return layers.fc(pool, size=class_dim, act="softmax")


def resnet(input, class_dim=1000, depth=50, variant="imagenet",
           is_test=False):
    if variant == "imagenet":
        return resnet_imagenet(input, class_dim, depth, is_test=is_test)
    return resnet_cifar10(input, class_dim, depth, is_test=is_test)
