"""Model zoo built on the layers DSL — parity targets are the reference's
benchmark configs (reference: benchmark/paddle/image/{alexnet,googlenet,
resnet,vgg,smallnet_mnist_cifar}.py) and book tests
(reference: python/paddle/fluid/tests/book/).

Every builder appends ops to the current default program and returns the
logits/cost variables, exactly like user scripts in the reference do.
"""
from .lenet import lenet5  # noqa: F401
from .mlp import mlp  # noqa: F401
from .vgg import vgg16, vgg_cifar  # noqa: F401
from .resnet import resnet, resnet_cifar10, resnet_imagenet  # noqa: F401
from .alexnet import alexnet  # noqa: F401
from .googlenet import googlenet  # noqa: F401
from .transformer import (  # noqa: F401
    TransformerConfig, TransformerLM, transformer_lm, transformer_block,
)
from .ctr import wide_deep, deepfm, synthetic_click_batch  # noqa: F401
