"""Simple MLP (the book's "multilayer_perceptron").

reference: python/paddle/fluid/tests/book/test_recognize_digits.py (mlp),
test_fit_a_line.py (single fc regressor).
"""
from __future__ import annotations

from .. import layers


def mlp(x, label=None, hidden_sizes=(200, 200), class_num=10,
        act="relu", pred_act="softmax"):
    h = x
    for size in hidden_sizes:
        h = layers.fc(h, size=size, act=act)
    prediction = layers.fc(h, size=class_num, act=pred_act)
    if label is None:
        return prediction, None, None
    cost = layers.cross_entropy(prediction, label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(prediction, label)
    return prediction, avg_cost, acc
