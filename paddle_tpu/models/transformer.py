"""Decoder-only transformer language model — the modern flagship family.

No 2018 reference equivalent (attention postdates the snapshot; its
sequence flagship was the attention-seq2seq book model,
python/paddle/fluid/tests/book/test_machine_translation.py). This is the
capability the TPU build adds on top: pre-norm causal blocks whose
attention is the ``flash_attention`` op — the Pallas kernel on TPU
(kernels/flash_attention.py), dense fallback elsewhere — with every
matmul batched for the MXU. Long sequences shard over a context-parallel
mesh axis via parallel/ring.py; tensor-parallel specs for the qkv/mlp
weights come from ShardingStrategy param_rules (see tests/test_models.py).
"""
from __future__ import annotations

from ..layers import nn as L
from ..layers import ops as OPS
from ..layers import tensor as T
from ..layers.layer_helper import LayerHelper
from ..param_attr import ParamAttr


def causal_flash_attention(q, k, v, num_heads):
    """[B, S, hidden] q/k/v -> [B, S, hidden] via the flash_attention op
    (causal)."""
    B_S_H = q.shape
    hidden = B_S_H[-1]
    seq = B_S_H[-2]
    dh = hidden // num_heads
    qh = L.reshape(q, shape=[0, seq, num_heads, dh])
    kh = L.reshape(k, shape=[0, seq, num_heads, dh])
    vh = L.reshape(v, shape=[0, seq, num_heads, dh])
    helper = LayerHelper("flash_attention")
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    out.shape = qh.shape
    helper.append_op(type="flash_attention",
                     inputs={"Q": [qh], "K": [kh], "V": [vh]},
                     outputs={"Out": [out]},
                     attrs={"causal": True})
    return L.reshape(out, shape=[0, seq, hidden])


def transformer_block(x, hidden, num_heads, ffn_mult=4, prefix="blk"):
    """Pre-norm block: x + attn(ln(x)); x + ffn(ln(x))."""
    h = L.layer_norm(x, begin_norm_axis=2,
                     param_attr=ParamAttr(name=prefix + "_ln1_w"),
                     bias_attr=ParamAttr(name=prefix + "_ln1_b"))
    q = L.fc(h, size=hidden, num_flatten_dims=2, bias_attr=False,
             param_attr=ParamAttr(name=prefix + "_q"))
    k = L.fc(h, size=hidden, num_flatten_dims=2, bias_attr=False,
             param_attr=ParamAttr(name=prefix + "_k"))
    v = L.fc(h, size=hidden, num_flatten_dims=2, bias_attr=False,
             param_attr=ParamAttr(name=prefix + "_v"))
    att = causal_flash_attention(q, k, v, num_heads)
    proj = L.fc(att, size=hidden, num_flatten_dims=2, bias_attr=False,
                param_attr=ParamAttr(name=prefix + "_proj"))
    x = L.elementwise_add(x, proj)
    h2 = L.layer_norm(x, begin_norm_axis=2,
                      param_attr=ParamAttr(name=prefix + "_ln2_w"),
                      bias_attr=ParamAttr(name=prefix + "_ln2_b"))
    up = L.fc(h2, size=hidden * ffn_mult, num_flatten_dims=2, act="relu",
              param_attr=ParamAttr(name=prefix + "_up"))
    down = L.fc(up, size=hidden, num_flatten_dims=2, bias_attr=False,
                param_attr=ParamAttr(name=prefix + "_down"))
    return L.elementwise_add(x, down)


def transformer_lm(tokens, vocab_size, hidden=64, num_layers=2,
                   num_heads=4, ffn_mult=4):
    """``tokens`` [B, S] int64 -> logits [B, S, vocab_size].

    Learned positional embeddings added to token embeddings, N pre-norm
    causal blocks, final layer norm, untied projection head.
    """
    seq = tokens.shape[1]
    emb = L.embedding(tokens, size=[vocab_size, hidden],
                      param_attr=ParamAttr(name="tok_emb"))
    # position ids: cumsum over a ones row - 1, per batch row
    ones = T.fill_constant_batch_size_like(tokens, shape=[-1, seq],
                                           dtype="float32", value=1.0)
    pos_ids = T.cast(L.scale(OPS.cumsum(ones, axis=1), scale=1.0, bias=-1.0),
                     "int64")
    pos = L.embedding(pos_ids, size=[seq, hidden],
                      param_attr=ParamAttr(name="pos_emb"))
    x = L.elementwise_add(emb, pos)
    for i in range(num_layers):
        x = transformer_block(x, hidden, num_heads, ffn_mult,
                              prefix="blk%d" % i)
    x = L.layer_norm(x, begin_norm_axis=2,
                     param_attr=ParamAttr(name="final_ln_w"),
                     bias_attr=ParamAttr(name="final_ln_b"))
    return L.fc(x, size=vocab_size, num_flatten_dims=2, bias_attr=False,
                param_attr=ParamAttr(name="lm_head"))


# ---------------------------------------------------------------------------
# Autoregressive serving face: the SAME weights transformer_lm trains,
# re-expressed as pure jax functions the generation engine
# (paddle_tpu.serving.generator) can jit once and drive per token.
#
# Three entry points, one math:
#
# - ``forward(params, tokens, config)``: full-sequence logits — the
#   pure-jax mirror of the transformer_lm Program (anchored by a parity
#   test against the Executor path), and the reference decoder for the
#   continuous-batching bit-parity proof.
# - ``prefill_step(...)``: one prompt through the full forward, its
#   per-layer K/V scattered into the paged pool through the sequence's
#   block table, last-real-position logits returned. Traced once per
#   prompt-length bucket.
# - ``decode_step(...)``: ONE token for every running sequence at once —
#   single-token attention that reads K/V *through the block table*
#   (gather) and writes the new position's K/V *through it* (scatter).
#   All operands have fixed [max_running, ...] shapes, so the engine's
#   hot loop is trace-free at any mix of sequence lengths.
#
# The math mirrors the op lowerings exactly (ops/attention_ops dense
# reference, ops/nn_ops layer_norm eps=1e-5, mul's flatten-then-gemm):
# masked-out cache columns contribute exp(-inf)=0 — exact zeros — so a
# cached single-token step computes the same attention row the full
# forward does, and greedy decode through the cache is token-identical
# to full-sequence recompute (proven in tests/test_generation.py).

LN_EPS = 1e-5


class TransformerConfig(object):
    """Static hyperparameters of one decoder-only LM — everything the
    serving tier needs to rebuild the jax functions around a params
    dict (JSON round-trip for the generative artifact)."""

    __slots__ = ("vocab_size", "hidden", "num_layers", "num_heads",
                 "ffn_mult", "max_seq", "eos_id")

    def __init__(self, vocab_size, hidden=64, num_layers=2, num_heads=4,
                 ffn_mult=4, max_seq=128, eos_id=None):
        if hidden % num_heads:
            raise ValueError("hidden=%d not divisible by num_heads=%d"
                             % (hidden, num_heads))
        self.vocab_size = int(vocab_size)
        self.hidden = int(hidden)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.ffn_mult = int(ffn_mult)
        self.max_seq = int(max_seq)
        self.eos_id = None if eos_id is None else int(eos_id)

    @property
    def head_dim(self):
        return self.hidden // self.num_heads

    def to_dict(self):
        return {"vocab_size": self.vocab_size, "hidden": self.hidden,
                "num_layers": self.num_layers, "num_heads": self.num_heads,
                "ffn_mult": self.ffn_mult, "max_seq": self.max_seq,
                "eos_id": self.eos_id}

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


def param_names(config):
    """Declaration-ordered parameter names — exactly the ParamAttr names
    transformer_lm creates, so trained scopes export losslessly."""
    names = ["tok_emb", "pos_emb"]
    for i in range(config.num_layers):
        p = "blk%d" % i
        names += [p + s for s in ("_ln1_w", "_ln1_b", "_q", "_k", "_v",
                                  "_proj", "_ln2_w", "_ln2_b", "_up",
                                  "_down")]
    names += ["final_ln_w", "final_ln_b", "lm_head"]
    return names


def init_params(config, seed=0):
    """Random float32 params (benchmarks/tests that don't train first).
    Scaled-normal projections, unit layer norms — the shapes
    transformer_lm's ParamAttrs would create."""
    import numpy as np
    rng = np.random.RandomState(seed)
    H, V, S = config.hidden, config.vocab_size, config.max_seq
    F = H * config.ffn_mult

    def w(shape, scale):
        return (rng.randn(*shape) * scale).astype(np.float32)

    p = {"tok_emb": w((V, H), 0.05), "pos_emb": w((S, H), 0.05)}
    for i in range(config.num_layers):
        pre = "blk%d" % i
        p[pre + "_ln1_w"] = np.ones((H,), np.float32)
        p[pre + "_ln1_b"] = np.zeros((H,), np.float32)
        for s in ("_q", "_k", "_v", "_proj"):
            p[pre + s] = w((H, H), (2.0 / H) ** 0.5)
        p[pre + "_ln2_w"] = np.ones((H,), np.float32)
        p[pre + "_ln2_b"] = np.zeros((H,), np.float32)
        p[pre + "_up"] = w((H, F), (2.0 / H) ** 0.5)
        p[pre + "_down"] = w((F, H), (2.0 / F) ** 0.5)
    p["final_ln_w"] = np.ones((H,), np.float32)
    p["final_ln_b"] = np.zeros((H,), np.float32)
    p["lm_head"] = w((H, V), (2.0 / H) ** 0.5)
    return p


def params_from_scope(config, scope=None):
    """Extract the trained transformer_lm weights from ``scope`` (default
    global scope) as the {name: np.ndarray} dict the serving face runs
    on. Raises with every missing name listed."""
    import numpy as np
    from ..core.scope import global_scope
    scope = scope or global_scope()
    out, missing = {}, []
    for n in param_names(config):
        v = scope.find_var(n) if scope.has_var(n) else None
        if v is None:
            missing.append(n)
        else:
            out[n] = np.asarray(v)
    if missing:
        raise ValueError(
            "scope is missing transformer params %s — was transformer_lm "
            "built with this config and the startup program run?" % missing)
    return out


def _ln(x, w, b):
    import jax.numpy as jnp
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + LN_EPS) * w + b


def _dense_causal_attention(q, k, v, num_heads):
    """[B, S, H] q/k/v -> [B, S, H]; the ops/attention_ops dense lowering
    verbatim (einsum scores, tril -inf mask, jax.nn.softmax)."""
    import jax
    import jax.numpy as jnp
    B, S, H = q.shape
    dh = H // num_heads
    t = lambda a: (a.reshape(B, S, num_heads, dh)
                   .transpose(0, 2, 1, 3).reshape(B * num_heads, S, dh))
    s = jnp.einsum("bqd,bkd->bqk", t(q), t(k)) * dh ** -0.5
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bqk,bkd->bqd", p, t(v))
    return (o.reshape(B, num_heads, S, dh)
            .transpose(0, 2, 1, 3).reshape(B, S, H))


def _forward_kv(params, tokens, config):
    """Full forward over ``tokens`` [B, S] -> (logits [B, S, V],
    k [L, B, S, nh, dh], v [L, B, S, nh, dh])."""
    import jax.numpy as jnp
    nh, dh = config.num_heads, config.head_dim
    B, S = tokens.shape
    ids = tokens.astype(jnp.int32)
    x = jnp.take(params["tok_emb"], ids, axis=0) \
        + jnp.take(params["pos_emb"], jnp.arange(S, dtype=jnp.int32),
                   axis=0)[None]
    ks, vs = [], []
    for i in range(config.num_layers):
        pre = "blk%d" % i
        h = _ln(x, params[pre + "_ln1_w"], params[pre + "_ln1_b"])
        q = h @ params[pre + "_q"]
        k = h @ params[pre + "_k"]
        v = h @ params[pre + "_v"]
        ks.append(k.reshape(B, S, nh, dh))
        vs.append(v.reshape(B, S, nh, dh))
        att = _dense_causal_attention(q, k, v, nh)
        x = x + att @ params[pre + "_proj"]
        h2 = _ln(x, params[pre + "_ln2_w"], params[pre + "_ln2_b"])
        up = jnp.maximum(h2 @ params[pre + "_up"], 0.0)
        x = x + up @ params[pre + "_down"]
    x = _ln(x, params["final_ln_w"], params["final_ln_b"])
    logits = x @ params["lm_head"]
    return logits, jnp.stack(ks), jnp.stack(vs)


def forward(params, tokens, config):
    """Full-sequence logits [B, S, V] — the pure-jax mirror of the
    transformer_lm Program (parity test: tests/test_generation.py)."""
    return _forward_kv(params, tokens, config)[0]


def prefill_step(params, k_pages, v_pages, tokens, length, pages, config):
    """One prompt (``tokens`` [S_bucket], real length ``length``) through
    the full forward; per-layer K/V scattered into the paged pool at the
    sequence's ``pages`` ([max_blocks], trash-padded) and the logits of
    the last REAL position returned (the first sampled token's
    distribution). Positions >= length route to the trash page — padding
    never lands in live cache. Jit once per prompt bucket; donate the
    pools."""
    import jax.numpy as jnp
    T = k_pages.shape[2]
    trash = k_pages.shape[1] - 1
    logits, k, v = _forward_kv(params, tokens[None], config)
    pos = jnp.arange(tokens.shape[0], dtype=jnp.int32)
    page = jnp.where(pos < length, pages[pos // T], trash)
    slot = pos % T
    k_pages = k_pages.at[:, page, slot].set(k[:, 0])
    v_pages = v_pages.at[:, page, slot].set(v[:, 0])
    return logits[0, length - 1], k_pages, v_pages


def decode_step(params, k_pages, v_pages, block_tables, positions, tokens,
                active, config, attn_config=None):
    """ONE fused token step for the whole running batch.

    ``k_pages``/``v_pages``: [L, num_pages+1, page_tokens, nh, dh] (the
    last page is the trash page — writes for inactive rows land there).
    ``block_tables``: [R, max_blocks] int32 page ids, trash-padded.
    ``positions``: [R] int32 — the new token's 0-based position (== how
    many tokens the row has cached). ``tokens``: [R] int32 — the last
    sampled token per row. ``active``: [R] bool.

    Returns (logits [R, V], k_pages, v_pages). Every operand shape is
    fixed by (max_running, pool shape), so the engine compiles this ONCE
    and runs it at any mix of sequence lengths. Attention reads the
    row's K/V through its block table and masks columns > position:
    exp(-inf)=0 exactly, so each row computes the same softmax row a
    full-sequence forward would. ``attn_config`` is a paddle_tpu.tune
    "paged_attention" pick routing the read through the Pallas paged-
    attention kernel; None (or an invalid pick) runs the always-legal
    block-table gather."""
    import jax.numpy as jnp
    from ..kernels.paged_attention import (paged_attention,
                                           paged_attention_reference,
                                           resolve_block_config)
    nh, dh = config.num_heads, config.head_dim
    R = tokens.shape[0]
    T = k_pages.shape[2]
    trash = k_pages.shape[1] - 1
    rows = jnp.arange(R, dtype=jnp.int32)
    pos = positions.astype(jnp.int32)
    x = jnp.take(params["tok_emb"], tokens.astype(jnp.int32), axis=0) \
        + jnp.take(params["pos_emb"], pos, axis=0)
    page = jnp.where(active, block_tables[rows, pos // T], trash)
    slot = pos % T
    # resolve the kernel pick ONCE per trace: invalid/stale configs
    # degrade to the gather here, so a bad cache entry can never fail
    # the decode trace mid-serving
    use_kernel = resolve_block_config(attn_config, R,
                                      block_tables.shape[1]) is not None
    for i in range(config.num_layers):
        pre = "blk%d" % i
        h = _ln(x, params[pre + "_ln1_w"], params[pre + "_ln1_b"])
        q = (h @ params[pre + "_q"]).reshape(R, nh, dh)
        k_new = (h @ params[pre + "_k"]).reshape(R, nh, dh)
        v_new = (h @ params[pre + "_v"]).reshape(R, nh, dh)
        k_pages = k_pages.at[i, page, slot].set(k_new)
        v_pages = v_pages.at[i, page, slot].set(v_new)
        if use_kernel:
            att = paged_attention(q, k_pages[i], v_pages[i], block_tables,
                                  pos, config=attn_config)
        else:
            att = paged_attention_reference(q, k_pages[i], v_pages[i],
                                            block_tables, pos)
        x = x + att.reshape(R, nh * dh) @ params[pre + "_proj"]
        h2 = _ln(x, params[pre + "_ln2_w"], params[pre + "_ln2_b"])
        up = jnp.maximum(h2 @ params[pre + "_up"], 0.0)
        x = x + up @ params[pre + "_down"]
    x = _ln(x, params["final_ln_w"], params["final_ln_b"])
    return x @ params["lm_head"], k_pages, v_pages


def device_sample(logits, temperatures, seeds, counters):
    """Seeded per-row sampling INSIDE the jitted step: ``logits``
    [R, V]; ``temperatures`` [R] f32 (<= 0 = greedy argmax);
    ``seeds``/``counters`` [R] int32. Each row's key is
    ``fold_in(PRNGKey(seed), counter)`` with counter = the sampled
    token's position in the FULL sequence (prompt + generated) — the
    stream is a pure function of (seed, position), so it is independent
    of batch slot and RESUMES at the right point after a preemption
    recompute. Returns (tokens [R] int32, logprobs [R] f32 — the
    UNtempered log-softmax at the chosen token, what the retire path
    reads instead of re-materializing logits)."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        def one(row, temp, seed, ctr):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), ctr)
            return jax.random.categorical(
                key, row / jnp.maximum(temp, 1e-6)).astype(jnp.int32)
        return jax.vmap(one)(logits, temperatures, seeds, counters)

    # the categorical draw prices the FULL [R, V] gumbel trick — behind
    # a batch-level cond so an all-greedy step (the common serving
    # steady state, and the parity gates) never pays it; tempered rows
    # keep the exact per-row stream (the cond branch is the same vmap)
    sampled = jax.lax.cond(jnp.any(temperatures > 0.0), _sampled,
                           lambda _: greedy, None)
    toks = jnp.where(temperatures > 0.0, sampled, greedy)
    logps = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), toks[:, None], axis=-1)[:, 0]
    return toks, logps


def decode_step_sampled(params, k_pages, v_pages, block_tables, positions,
                        tokens, active, temperatures, seeds,
                        config, attn_config=None):
    """The fused decode FAST PATH: decode_step + :func:`device_sample`
    in one jit, returning ([R] int32 sampled tokens, [R] f32 logprobs,
    k_pages, v_pages) — the host transfer per step shrinks from
    [R, V] logits to two [R] rows and the host loop becomes pure
    bookkeeping. The per-row RNG counter is derived ON DEVICE as
    ``positions + 1``: at decode time the row's token offset always
    equals its cached length + 1 (one token accepted per step, and a
    preemption resume re-prefills the full prefix), so the fused step
    adds NO per-step host->device operands beyond the host path —
    temperatures/seeds only change when the running set changes and
    the engine caches their device copies."""
    import jax.numpy as jnp
    logits, k_pages, v_pages = decode_step(
        params, k_pages, v_pages, block_tables, positions, tokens,
        active, config, attn_config=attn_config)
    toks, logps = device_sample(logits, temperatures, seeds,
                                jnp.asarray(positions, jnp.int32) + 1)
    return toks, logps, k_pages, v_pages


def prefill_step_sampled(params, k_pages, v_pages, tokens, length, pages,
                         temperature, seed, config):
    """prefill_step + device sampling of the FIRST token: returns
    (token int32, logprob f32, k_pages, v_pages) — no [V] logits row
    crosses to the host on the fused path. The RNG counter is the
    sampled token's position in the FULL sequence (= ``length``, the
    fed prefix), matching the decode step's on-device ``positions + 1``
    derivation — so a preemption resume, which re-prefills
    prompt+progress, continues the exact stream the decode steps were
    drawing from."""
    import jax.numpy as jnp
    last, k_pages, v_pages = prefill_step(params, k_pages, v_pages,
                                          tokens, length, pages, config)
    toks, logps = device_sample(
        last[None], jnp.asarray([temperature], jnp.float32),
        jnp.asarray([seed], jnp.int32),
        jnp.asarray([length], jnp.int32))
    return toks[0], logps[0], k_pages, v_pages


# ---------------------------------------------------------------------------
# Speculative decoding faces (serving/speculative.py drives these).
#
# One round: the DRAFT model proposes k tokens autoregressively
# (``draft_propose_step`` — a lax.scan of k+1 decode steps over the
# draft's OWN page pool, one trace total), then the TARGET model runs
# ONE k+1-lane verify step (``verify_step_sampled``) that scatters all
# k+1 positions' K/V and attends every lane at once, accepts the
# longest valid draft prefix, and samples the correction/bonus token on
# device. Only a packed [R, 2(k+1)+1] f32 row crosses to the host —
# draft logits never leave the device (gen_host_logit_syncs stays 0).
#
# RNG discipline: every draw is keyed by the drawn token's absolute
# position in the full sequence — ``fold_in(PRNGKey(seed), position)``
# for the plain/bonus draw (the SAME key the non-speculative fused step
# uses at that position, so a cap-0 row is bit-identical to plain
# decode), and salted variants of it for the draft proposal, the accept
# uniform, and the residual draw. Pure functions of (seed, position)
# means a preemption resume — which re-prefills prompt+progress and
# restarts the round at the same position — replays the exact
# accept/reject history.
#
# Stale-write safety: verify scatters K/V for ALL k+1 lanes, including
# drafts that end up rejected. No rollback is needed — attention masks
# columns past each query's position, and every overshot position is
# re-scattered (with its true token) by a later round before any
# unmasked read, because rounds always restart at the first unaccepted
# position. The engine only trims page-table overshoot (allocator
# bookkeeping), never cache contents.

_DRAFT_SALT = 0x5D    # the draft model's own proposal draws
_ACCEPT_SALT = 0x5A   # the accept/reject uniform per draft position
_RESID_SALT = 0x5E    # the residual draw after a rejection


def draft_propose_step(params, k_pages, v_pages, block_tables, positions,
                       tokens, active, temperatures, seeds, spec_caps,
                       k, config):
    """Propose ``k`` tokens per row from the DRAFT model: a lax.scan of
    k+1 :func:`decode_step` substeps over the draft's own paged pool.
    Substep j feeds the row's current token at position ``positions+j``
    (substep 0 feeds the pending last sampled token, later substeps
    feed the row's own proposals), writes its K/V live only while
    ``j <= spec_caps[r]`` (capped/plain rows route overshoot to the
    trash page), and samples the next proposal — greedy argmax, or a
    categorical keyed ``fold_in(fold_in(PRNGKey(seed), position+j+1),
    _DRAFT_SALT)`` for tempered rows. The final substep only writes
    K/V, keeping the draft cache exactly caught up with the target's.
    Returns (drafts [R, k] int32, draft_logits [R, k, V] f32, k_pages,
    v_pages); ONE trace per (k, geometry) — the scan body is traced
    once."""
    import jax
    import jax.numpy as jnp
    pos0 = jnp.asarray(positions, jnp.int32)

    def substep(carry, j):
        kp, vp, cur = carry
        write_ok = active & (j <= spec_caps)
        logits, kp, vp = decode_step(params, kp, vp, block_tables,
                                     pos0 + j, cur, write_ok, config)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def one(row, temp, seed, idx):
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), idx),
                _DRAFT_SALT)
            return jax.random.categorical(
                key, row / jnp.maximum(temp, 1e-6)).astype(jnp.int32)

        sampled = jax.lax.cond(
            jnp.any(temperatures > 0.0),
            lambda _: jax.vmap(one)(logits, temperatures, seeds,
                                    pos0 + j + 1),
            lambda _: greedy, None)
        nxt = jnp.where(temperatures > 0.0, sampled, greedy)
        return (kp, vp, nxt), (nxt, logits)

    (k_pages, v_pages, _), (toks, logits) = jax.lax.scan(
        substep, (k_pages, v_pages, jnp.asarray(tokens, jnp.int32)),
        jnp.arange(k + 1, dtype=jnp.int32))
    drafts = jnp.transpose(toks[:k])                     # [R, k]
    draft_logits = jnp.transpose(logits[:k], (1, 0, 2))  # [R, k, V]
    return drafts, draft_logits, k_pages, v_pages


def verify_step(params, k_pages, v_pages, block_tables, positions, tokens,
                active, spec_caps, config, attn_config=None):
    """ONE target-model step over ``K1 = k+1`` lanes per row: lane i
    feeds ``tokens[r, i]`` at position ``positions[r]+i`` (lane 0 is
    the pending last sampled token, lanes 1..k the draft proposals).
    Per layer, ALL lanes' K/V scatter first, then every lane attends
    through the block table with its own position mask — so lane i
    computes exactly the logits a plain decode step would after
    accepting lanes < i. Lanes past ``spec_caps[r]`` (and inactive
    rows) write to the trash page. Returns (logits [R, K1, V],
    k_pages, v_pages)."""
    import jax.numpy as jnp
    from ..kernels.paged_attention import paged_attention_kwide
    nh, dh = config.num_heads, config.head_dim
    R, K1 = tokens.shape
    T = k_pages.shape[2]
    trash = k_pages.shape[1] - 1
    rows = jnp.arange(R, dtype=jnp.int32)
    lanes = jnp.arange(K1, dtype=jnp.int32)
    pos = positions.astype(jnp.int32)[:, None] + lanes[None, :]  # [R, K1]
    live = active[:, None] & (lanes[None, :] <= spec_caps[:, None])
    x = jnp.take(params["tok_emb"], tokens.astype(jnp.int32), axis=0) \
        + jnp.take(params["pos_emb"], pos, axis=0)
    page = jnp.where(live, block_tables[rows[:, None], pos // T], trash)
    slot = pos % T
    for i in range(config.num_layers):
        pre = "blk%d" % i
        h = _ln(x, params[pre + "_ln1_w"], params[pre + "_ln1_b"])
        q = (h @ params[pre + "_q"]).reshape(R, K1, nh, dh)
        k_new = (h @ params[pre + "_k"]).reshape(R, K1, nh, dh)
        v_new = (h @ params[pre + "_v"]).reshape(R, K1, nh, dh)
        k_pages = k_pages.at[i, page, slot].set(k_new)
        v_pages = v_pages.at[i, page, slot].set(v_new)
        att = paged_attention_kwide(q, k_pages[i], v_pages[i],
                                    block_tables, pos, config=attn_config)
        x = x + att.reshape(R, K1, nh * dh) @ params[pre + "_proj"]
        h2 = _ln(x, params[pre + "_ln2_w"], params[pre + "_ln2_b"])
        up = jnp.maximum(h2 @ params[pre + "_up"], 0.0)
        x = x + up @ params[pre + "_down"]
    x = _ln(x, params["final_ln_w"], params["final_ln_b"])
    return x @ params["lm_head"], k_pages, v_pages


def speculative_accept(logits, drafts, draft_logits, positions,
                       temperatures, seeds, spec_caps):
    """The accept/reject rule, on device. ``logits`` [R, K1, V] target
    verify logits; ``drafts`` [R, K] / ``draft_logits`` [R, K, V] the
    proposals; ``spec_caps`` [R] int32 — draft i only counts while
    ``i < cap`` (cap 0 = plain row).

    Greedy rows (temp <= 0) accept the longest prefix with
    ``drafts[i] == argmax(logits[:, i])`` and emit
    ``argmax(logits[:, a])`` as the correction/bonus — by construction
    the exact token sequence non-speculative greedy decode emits.
    Tempered rows use canonical rejection sampling: draft i accepts iff
    ``log u <= log q(d) - log p(d)`` (q = tempered target, p = tempered
    draft, u keyed ``_ACCEPT_SALT`` at the draft's position); the first
    rejection resamples from ``norm(max(q - p, 0))`` keyed
    ``_RESID_SALT``; a fully-accepted row draws its bonus with the
    PLAIN position key — the same key the non-speculative fused step
    uses, so cap-0 rows reproduce the plain stream bit-exactly.

    Returns (emitted [R, K1] int32, n_out [R] int32 in 1..K1,
    logprobs [R, K1] f32 — UNtempered target log-softmax at the emitted
    token, the same convention as :func:`device_sample`)."""
    import jax
    import jax.numpy as jnp
    R, K1, V = logits.shape
    K = K1 - 1
    pos0 = jnp.asarray(positions, jnp.int32)
    lanes = jnp.arange(K, dtype=jnp.int32)
    lanes1 = jnp.arange(K1, dtype=jnp.int32)
    temp = jnp.maximum(temperatures, 1e-6)[:, None, None]
    is_greedy = temperatures <= 0.0

    greedy_t = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [R, K1]
    g_acc = drafts == greedy_t[:, :K]
    lq = jax.nn.log_softmax(logits[:, :K] / temp, axis=-1)
    lp = jax.nn.log_softmax(draft_logits / temp, axis=-1)
    lq_d = jnp.take_along_axis(lq, drafts[..., None], axis=-1)[..., 0]
    lp_d = jnp.take_along_axis(lp, drafts[..., None], axis=-1)[..., 0]
    didx = pos0[:, None] + 1 + lanes[None, :]  # draft i's position

    def _accept_u(seed, idx):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), idx),
            _ACCEPT_SALT)
        return jax.random.uniform(key)

    u = jax.vmap(lambda s, ix: jax.vmap(
        lambda j: _accept_u(s, j))(ix))(seeds, didx)
    t_acc = jnp.log(u) <= lq_d - lp_d
    acc = jnp.where(is_greedy[:, None], g_acc, t_acc)
    acc = acc & (lanes[None, :] < spec_caps[:, None])
    a = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)  # [R]

    # correction/bonus token, from lane a's distributions
    lt_a = jnp.take_along_axis(logits, a[:, None, None], axis=1)[:, 0]
    ld_a = jnp.take_along_axis(
        draft_logits, jnp.minimum(a, K - 1)[:, None, None], axis=1)[:, 0]
    qa = jax.nn.softmax(lt_a / temp[:, :, 0], axis=-1)
    pa = jax.nn.softmax(ld_a / temp[:, :, 0], axis=-1)
    resid = jnp.maximum(qa - pa, 0.0)
    rsum = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(rsum > 0.0, resid, qa)

    def _final_t(seed, idx, rejected, log_resid, lt_scaled):
        base = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
        t_resid = jax.random.categorical(
            jax.random.fold_in(base, _RESID_SALT), log_resid)
        t_plain = jax.random.categorical(base, lt_scaled)
        return jnp.where(rejected, t_resid, t_plain).astype(jnp.int32)

    final_t = jax.lax.cond(
        jnp.any(temperatures > 0.0),
        lambda _: jax.vmap(_final_t)(
            seeds, pos0 + a + 1, a < spec_caps,
            jnp.log(resid + 1e-38), lt_a / temp[:, :, 0]),
        lambda _: jnp.take_along_axis(greedy_t, a[:, None],
                                      axis=1)[:, 0], None)
    final_g = jnp.take_along_axis(greedy_t, a[:, None], axis=1)[:, 0]
    final = jnp.where(is_greedy, final_g, final_t)

    drafts_pad = jnp.concatenate([drafts, drafts[:, :1]], axis=1)
    emitted = jnp.where(lanes1[None, :] < a[:, None], drafts_pad,
                        final[:, None])
    logps = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), emitted[..., None],
        axis=-1)[..., 0]
    return emitted, a + 1, logps


def verify_step_sampled(params, k_pages, v_pages, block_tables, positions,
                        tokens, drafts, draft_logits, active, temperatures,
                        seeds, spec_caps, config, attn_config=None):
    """The fused speculative verify: :func:`verify_step` over
    ``[last_token, drafts...]`` + :func:`speculative_accept` in one
    jit. Returns (packed [R, 2*K1+1] f32 — emitted tokens [K1], n_out,
    logprobs [K1] per row, ONE host transfer — , k_pages, v_pages)."""
    import jax.numpy as jnp
    tokens_k1 = jnp.concatenate(
        [jnp.asarray(tokens, jnp.int32)[:, None], drafts], axis=1)
    logits, k_pages, v_pages = verify_step(
        params, k_pages, v_pages, block_tables, positions, tokens_k1,
        active, spec_caps, config, attn_config=attn_config)
    emitted, n_out, logps = speculative_accept(
        logits, drafts, draft_logits, positions, temperatures, seeds,
        spec_caps)
    packed = jnp.concatenate(
        [emitted.astype(jnp.float32), n_out.astype(jnp.float32)[:, None],
         logps], axis=1)
    return packed, k_pages, v_pages


class TransformerLM(object):
    """Weights + config bound into the serving face the generation
    engine drives: ``forward`` for references/parity, ``prefill_step``/
    ``decode_step`` for the paged hot path. Params are moved to device
    once (a generation process must not re-upload weights per step)."""

    def __init__(self, params, config):
        import jax
        if isinstance(config, dict):
            config = TransformerConfig.from_dict(config)
        self.config = config
        missing = [n for n in param_names(config) if n not in params]
        if missing:
            raise ValueError("params dict is missing %s" % missing)
        self.params = {n: jax.device_put(params[n])
                       for n in param_names(config)}

    # -- pool geometry the engine builds around ------------------------------
    @property
    def kv_spec(self):
        """(num_layers, num_heads, head_dim) of one cached position."""
        c = self.config
        return (c.num_layers, c.num_heads, c.head_dim)

    # -- entry points (pure; the engine jits them) ---------------------------
    def forward(self, tokens):
        return forward(self.params, tokens, self.config)

    def prefill_fn(self):
        cfg = self.config

        def fn(params, k_pages, v_pages, tokens, length, pages):
            return prefill_step(params, k_pages, v_pages, tokens, length,
                                pages, cfg)
        return fn

    def decode_fn(self, attn_config=None):
        cfg = self.config

        def fn(params, k_pages, v_pages, block_tables, positions, tokens,
               active):
            return decode_step(params, k_pages, v_pages, block_tables,
                               positions, tokens, active, cfg,
                               attn_config=attn_config)
        return fn

    # -- fused (device-sampling) faces ---------------------------------------
    def prefill_sample_fn(self):
        cfg = self.config

        def fn(params, k_pages, v_pages, tokens, length, pages,
               temperature, seed):
            return prefill_step_sampled(params, k_pages, v_pages, tokens,
                                        length, pages, temperature, seed,
                                        cfg)
        return fn

    def decode_sample_fn(self, attn_config=None):
        cfg = self.config

        def fn(params, k_pages, v_pages, block_tables, positions, tokens,
               active, temperatures, seeds):
            import jax.numpy as jnp
            toks, logps, k_pages, v_pages = decode_step_sampled(
                params, k_pages, v_pages, block_tables, positions,
                tokens, active, temperatures, seeds, cfg,
                attn_config=attn_config)
            # ONE [2R] f32 row crosses to the host per step (tokens are
            # exact in f32 up to vocab 2^24), not two fetches
            packed = jnp.concatenate([toks.astype(jnp.float32), logps])
            return packed, k_pages, v_pages
        return fn

    # -- speculative faces ---------------------------------------------------
    def draft_propose_fn(self, k):
        """This model as the DRAFT: propose ``k`` tokens per row over
        its own page pool (serving/speculative.py jits this once per
        (k, geometry))."""
        cfg = self.config

        def fn(params, k_pages, v_pages, block_tables, positions, tokens,
               active, temperatures, seeds, spec_caps):
            return draft_propose_step(params, k_pages, v_pages,
                                      block_tables, positions, tokens,
                                      active, temperatures, seeds,
                                      spec_caps, k, cfg)
        return fn

    def verify_sample_fn(self, attn_config=None):
        """This model as the TARGET: one fused k+1-lane verify +
        accept/reject + device sampling step (k is carried by the
        drafts operand's shape, so the engine jits this once per
        (k, geometry))."""
        cfg = self.config

        def fn(params, k_pages, v_pages, block_tables, positions, tokens,
               drafts, draft_logits, active, temperatures, seeds,
               spec_caps):
            return verify_step_sampled(params, k_pages, v_pages,
                                       block_tables, positions, tokens,
                                       drafts, draft_logits, active,
                                       temperatures, seeds, spec_caps,
                                       cfg, attn_config=attn_config)
        return fn
