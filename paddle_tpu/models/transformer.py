"""Decoder-only transformer language model — the modern flagship family.

No 2018 reference equivalent (attention postdates the snapshot; its
sequence flagship was the attention-seq2seq book model,
python/paddle/fluid/tests/book/test_machine_translation.py). This is the
capability the TPU build adds on top: pre-norm causal blocks whose
attention is the ``flash_attention`` op — the Pallas kernel on TPU
(kernels/flash_attention.py), dense fallback elsewhere — with every
matmul batched for the MXU. Long sequences shard over a context-parallel
mesh axis via parallel/ring.py; tensor-parallel specs for the qkv/mlp
weights come from ShardingStrategy param_rules (see tests/test_models.py).
"""
from __future__ import annotations

from ..layers import nn as L
from ..layers import ops as OPS
from ..layers import tensor as T
from ..layers.layer_helper import LayerHelper
from ..param_attr import ParamAttr


def causal_flash_attention(q, k, v, num_heads):
    """[B, S, hidden] q/k/v -> [B, S, hidden] via the flash_attention op
    (causal)."""
    B_S_H = q.shape
    hidden = B_S_H[-1]
    seq = B_S_H[-2]
    dh = hidden // num_heads
    qh = L.reshape(q, shape=[0, seq, num_heads, dh])
    kh = L.reshape(k, shape=[0, seq, num_heads, dh])
    vh = L.reshape(v, shape=[0, seq, num_heads, dh])
    helper = LayerHelper("flash_attention")
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    out.shape = qh.shape
    helper.append_op(type="flash_attention",
                     inputs={"Q": [qh], "K": [kh], "V": [vh]},
                     outputs={"Out": [out]},
                     attrs={"causal": True})
    return L.reshape(out, shape=[0, seq, hidden])


def transformer_block(x, hidden, num_heads, ffn_mult=4, prefix="blk"):
    """Pre-norm block: x + attn(ln(x)); x + ffn(ln(x))."""
    h = L.layer_norm(x, begin_norm_axis=2,
                     param_attr=ParamAttr(name=prefix + "_ln1_w"),
                     bias_attr=ParamAttr(name=prefix + "_ln1_b"))
    q = L.fc(h, size=hidden, num_flatten_dims=2, bias_attr=False,
             param_attr=ParamAttr(name=prefix + "_q"))
    k = L.fc(h, size=hidden, num_flatten_dims=2, bias_attr=False,
             param_attr=ParamAttr(name=prefix + "_k"))
    v = L.fc(h, size=hidden, num_flatten_dims=2, bias_attr=False,
             param_attr=ParamAttr(name=prefix + "_v"))
    att = causal_flash_attention(q, k, v, num_heads)
    proj = L.fc(att, size=hidden, num_flatten_dims=2, bias_attr=False,
                param_attr=ParamAttr(name=prefix + "_proj"))
    x = L.elementwise_add(x, proj)
    h2 = L.layer_norm(x, begin_norm_axis=2,
                      param_attr=ParamAttr(name=prefix + "_ln2_w"),
                      bias_attr=ParamAttr(name=prefix + "_ln2_b"))
    up = L.fc(h2, size=hidden * ffn_mult, num_flatten_dims=2, act="relu",
              param_attr=ParamAttr(name=prefix + "_up"))
    down = L.fc(up, size=hidden, num_flatten_dims=2, bias_attr=False,
                param_attr=ParamAttr(name=prefix + "_down"))
    return L.elementwise_add(x, down)


def transformer_lm(tokens, vocab_size, hidden=64, num_layers=2,
                   num_heads=4, ffn_mult=4):
    """``tokens`` [B, S] int64 -> logits [B, S, vocab_size].

    Learned positional embeddings added to token embeddings, N pre-norm
    causal blocks, final layer norm, untied projection head.
    """
    seq = tokens.shape[1]
    emb = L.embedding(tokens, size=[vocab_size, hidden],
                      param_attr=ParamAttr(name="tok_emb"))
    # position ids: cumsum over a ones row - 1, per batch row
    ones = T.fill_constant_batch_size_like(tokens, shape=[-1, seq],
                                           dtype="float32", value=1.0)
    pos_ids = T.cast(L.scale(OPS.cumsum(ones, axis=1), scale=1.0, bias=-1.0),
                     "int64")
    pos = L.embedding(pos_ids, size=[seq, hidden],
                      param_attr=ParamAttr(name="pos_emb"))
    x = L.elementwise_add(emb, pos)
    for i in range(num_layers):
        x = transformer_block(x, hidden, num_heads, ffn_mult,
                              prefix="blk%d" % i)
    x = L.layer_norm(x, begin_norm_axis=2,
                     param_attr=ParamAttr(name="final_ln_w"),
                     bias_attr=ParamAttr(name="final_ln_b"))
    return L.fc(x, size=vocab_size, num_flatten_dims=2, bias_attr=False,
                param_attr=ParamAttr(name="lm_head"))
