"""CTR models: wide&deep and DeepFM over sparse categorical slots.

reference: the CTR workload the reference's distributed design targets —
doc/design/cluster_train/large_model_dist_train.md (row-sharded lookup
tables on pservers) + operators/lookup_table_op.cc (is_sparse /
is_distributed attributes). The model shape follows the public
wide&deep / DeepFM recipes the reference's CTR demos used: dense
statistics + hashed categorical slots; embeddings carry
``is_sparse`` (SelectedRows gradients) and ``is_distributed``
(row-sharded table → ZeRO/pserver placement) exactly where the
reference put them.

TPU-first notes: each slot's lookup is one gather that XLA fuses with
the concat; the deep tower is a single fused MLP on the MXU. With
``is_distributed=True`` the table is row-sharded over the mesh by the
DistributeTranspiler's PartitionSpec rules and the gather rides a
collective — the large_model_dist_train design with XLA collectives in
the pserver role.
"""
from __future__ import annotations

from .. import layers


def _sparse_inputs(num_slots):
    return [layers.data(name="C%d" % i, shape=[1], dtype="int64",
                        lod_level=0)
            for i in range(num_slots)]


def _embed(ids, vocab_size, dim, name, is_sparse, is_distributed):
    from ..param_attr import ParamAttr
    return layers.embedding(
        input=ids, size=[vocab_size, dim], is_sparse=is_sparse,
        is_distributed=is_distributed,
        param_attr=ParamAttr(name=name))


def wide_deep(num_sparse_slots=26, dense_dim=13, vocab_size=10000,
              embed_dim=16, hidden_sizes=(400, 400, 400),
              is_sparse=True, is_distributed=False, with_auc=True):
    """Wide&Deep CTR: a linear ("wide") part over the raw slots plus a
    deep MLP over concatenated slot embeddings and dense features.

    Returns (avg_cost, auc_or_None, prob, feed_names).
    """
    dense = layers.data(name="dense_input", shape=[dense_dim],
                        dtype="float32")
    sparse = _sparse_inputs(num_sparse_slots)
    label = layers.data(name="click", shape=[1], dtype="float32")

    # deep tower: embeddings + dense stats -> MLP
    embs = [_embed(ids, vocab_size, embed_dim, "emb_C%d" % i,
                   is_sparse, is_distributed)
            for i, ids in enumerate(sparse)]
    deep = layers.concat(embs + [dense], axis=1)
    for i, h in enumerate(hidden_sizes):
        deep = layers.fc(input=deep, size=h, act="relu")
    deep_logit = layers.fc(input=deep, size=1, act=None)

    # wide part: per-slot scalar weights (size-1 embeddings == the
    # one-hot linear term) + a linear map of the dense stats
    wide_terms = [_embed(ids, vocab_size, 1, "wide_C%d" % i,
                         is_sparse, is_distributed)
                  for i, ids in enumerate(sparse)]
    wide_logit = layers.fc(input=layers.concat(wide_terms, axis=1),
                           size=1, act=None)
    wide_logit = layers.elementwise_add(
        wide_logit, layers.fc(input=dense, size=1, act=None))

    logit = layers.elementwise_add(deep_logit, wide_logit)
    prob = layers.sigmoid(logit)
    cost = layers.sigmoid_cross_entropy_with_logits(logit, label)
    avg_cost = layers.mean(cost)
    auc_var = layers.auc(prob, label) if with_auc else None
    feeds = ["dense_input"] + ["C%d" % i for i in range(num_sparse_slots)] \
        + ["click"]
    return avg_cost, auc_var, prob, feeds


def deepfm(num_sparse_slots=26, dense_dim=13, vocab_size=10000,
           embed_dim=16, hidden_sizes=(400, 400),
           is_sparse=True, is_distributed=False, with_auc=True):
    """DeepFM: first-order linear term + pairwise FM interaction computed
    with the sum-square/square-sum identity (one matmul-free reduction,
    TPU-friendly: no O(slots^2) loop) + a deep MLP sharing the same
    embeddings.

    Returns (avg_cost, auc_or_None, prob, feed_names).
    """
    dense = layers.data(name="dense_input", shape=[dense_dim],
                        dtype="float32")
    sparse = _sparse_inputs(num_sparse_slots)
    label = layers.data(name="click", shape=[1], dtype="float32")

    embs = [_embed(ids, vocab_size, embed_dim, "fm_emb_C%d" % i,
                   is_sparse, is_distributed)
            for i, ids in enumerate(sparse)]
    firsts = [_embed(ids, vocab_size, 1, "fm_w_C%d" % i,
                     is_sparse, is_distributed)
              for i, ids in enumerate(sparse)]

    # first order
    first_order = layers.fc(input=layers.concat(firsts + [dense], axis=1),
                            size=1, act=None)

    # second order: 0.5 * sum((sum_i v_i)^2 - sum_i v_i^2)
    stacked = layers.concat(
        [layers.reshape(e, shape=[-1, 1, embed_dim]) for e in embs],
        axis=1)                                     # (N, slots, dim)
    sum_emb = layers.reduce_sum(stacked, dim=1)     # (N, dim)
    sum_sq = layers.elementwise_mul(sum_emb, sum_emb)
    sq = layers.elementwise_mul(stacked, stacked)
    sq_sum = layers.reduce_sum(sq, dim=1)
    fm = layers.reduce_sum(
        layers.elementwise_sub(sum_sq, sq_sum), dim=1, keep_dim=True)
    fm = layers.scale(fm, scale=0.5)

    deep = layers.concat(embs + [dense], axis=1)
    for h in hidden_sizes:
        deep = layers.fc(input=deep, size=h, act="relu")
    deep_logit = layers.fc(input=deep, size=1, act=None)

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, fm), deep_logit)
    prob = layers.sigmoid(logit)
    cost = layers.sigmoid_cross_entropy_with_logits(logit, label)
    avg_cost = layers.mean(cost)
    auc_var = layers.auc(prob, label) if with_auc else None
    feeds = ["dense_input"] + ["C%d" % i for i in range(num_sparse_slots)] \
        + ["click"]
    return avg_cost, auc_var, prob, feeds


def synthetic_click_batch(rng, batch_size, num_sparse_slots=26,
                          dense_dim=13, vocab_size=10000):
    """Synthetic CTR batch with learnable structure: the click depends on
    a fixed random weighting of slot-hash parities and dense features, so
    AUC above 0.5 is achievable and loss must fall."""
    import numpy as np
    dense = rng.rand(batch_size, dense_dim).astype(np.float32)
    ids = [rng.randint(0, vocab_size, size=(batch_size, 1)).astype(np.int64)
           for _ in range(num_sparse_slots)]
    # deterministic signal: parity of a couple of slots + dense mean
    signal = ((ids[0] % 2).astype(np.float32)
              + (ids[1 % num_sparse_slots] % 3 == 0).astype(np.float32)
              + dense.mean(axis=1, keepdims=True))
    click = (signal + 0.3 * rng.randn(batch_size, 1)
             > np.median(signal)).astype(np.float32)
    feed = {"dense_input": dense, "click": click}
    for i, arr in enumerate(ids):
        feed["C%d" % i] = arr
    return feed
