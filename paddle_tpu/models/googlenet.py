"""GoogLeNet (Inception v1, no aux heads by default).

reference: benchmark/paddle/image/googlenet.py — inception modules as
concat of 1x1 / 3x3 / 5x5 / pool-proj towers.
"""
from __future__ import annotations

from .. import layers

__all__ = ["googlenet"]


def _inception(input, c1, c3r, c3, c5r, c5, proj):
    t1 = layers.conv2d(input, num_filters=c1, filter_size=1, act="relu")
    t3 = layers.conv2d(input, num_filters=c3r, filter_size=1, act="relu")
    t3 = layers.conv2d(t3, num_filters=c3, filter_size=3, padding=1,
                       act="relu")
    t5 = layers.conv2d(input, num_filters=c5r, filter_size=1, act="relu")
    t5 = layers.conv2d(t5, num_filters=c5, filter_size=5, padding=2,
                       act="relu")
    tp = layers.pool2d(input, pool_size=3, pool_stride=1, pool_padding=1,
                       pool_type="max")
    tp = layers.conv2d(tp, num_filters=proj, filter_size=1, act="relu")
    return layers.concat_nn([t1, t3, t5, tp], axis=1)


def googlenet(input, class_dim=1000, is_test=False):
    net = layers.conv2d(input, num_filters=64, filter_size=7, stride=2,
                        padding=3, act="relu")
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")
    net = layers.conv2d(net, num_filters=64, filter_size=1, act="relu")
    net = layers.conv2d(net, num_filters=192, filter_size=3, padding=1,
                        act="relu")
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")

    net = _inception(net, 64, 96, 128, 16, 32, 32)    # 3a
    net = _inception(net, 128, 128, 192, 32, 96, 64)  # 3b
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")

    net = _inception(net, 192, 96, 208, 16, 48, 64)   # 4a
    net = _inception(net, 160, 112, 224, 24, 64, 64)  # 4b
    net = _inception(net, 128, 128, 256, 24, 64, 64)  # 4c
    net = _inception(net, 112, 144, 288, 32, 64, 64)  # 4d
    net = _inception(net, 256, 160, 320, 32, 128, 128)  # 4e
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")

    net = _inception(net, 256, 160, 320, 32, 128, 128)  # 5a
    net = _inception(net, 384, 192, 384, 48, 128, 128)  # 5b
    net = layers.pool2d(net, pool_size=7, pool_stride=1, pool_type="avg",
                        global_pooling=True)
    net = layers.dropout(net, dropout_prob=0.4, is_test=is_test)
    return layers.fc(net, size=class_dim, act="softmax")
