"""AlexNet.

reference: benchmark/paddle/image/alexnet.py — conv11/5/3/3/3 + LRN + 2x fc4096.
"""
from __future__ import annotations

from .. import layers

__all__ = ["alexnet"]


def alexnet(input, class_dim=1000, is_test=False, use_lrn=True):
    net = layers.conv2d(input, num_filters=96, filter_size=11, stride=4,
                        padding=1, act="relu")
    if use_lrn:
        net = layers.lrn(net, n=5, alpha=1e-4, beta=0.75)
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")

    net = layers.conv2d(net, num_filters=256, filter_size=5, padding=2,
                        groups=1, act="relu")
    if use_lrn:
        net = layers.lrn(net, n=5, alpha=1e-4, beta=0.75)
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")

    net = layers.conv2d(net, num_filters=384, filter_size=3, padding=1,
                        act="relu")
    net = layers.conv2d(net, num_filters=384, filter_size=3, padding=1,
                        act="relu")
    net = layers.conv2d(net, num_filters=256, filter_size=3, padding=1,
                        act="relu")
    net = layers.pool2d(net, pool_size=3, pool_stride=2, pool_type="max")

    net = layers.fc(net, size=4096, act="relu")
    net = layers.dropout(net, dropout_prob=0.5, is_test=is_test)
    net = layers.fc(net, size=4096, act="relu")
    net = layers.dropout(net, dropout_prob=0.5, is_test=is_test)
    return layers.fc(net, size=class_dim, act="softmax")
