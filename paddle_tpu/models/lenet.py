"""LeNet-5 for MNIST.

reference: python/paddle/fluid/tests/book/test_recognize_digits.py (conv_net)
— conv-pool x2 + fc, the book's canonical digit recognizer.
"""
from __future__ import annotations

from .. import layers


def _conv_pool(input, num_filters, filter_size, pool_size, pool_stride, act):
    conv = layers.conv2d(input, num_filters=num_filters,
                         filter_size=filter_size, act=act)
    return layers.pool2d(conv, pool_size=pool_size, pool_stride=pool_stride,
                         pool_type="max")


def lenet5(img, label=None, class_num=10):
    """Returns (prediction, avg_cost, acc) — cost/acc are None without label."""
    c1 = _conv_pool(img, num_filters=20, filter_size=5, pool_size=2,
                    pool_stride=2, act="relu")
    c2 = _conv_pool(c1, num_filters=50, filter_size=5, pool_size=2,
                    pool_stride=2, act="relu")
    prediction = layers.fc(c2, size=class_num, act="softmax")
    if label is None:
        return prediction, None, None
    cost = layers.cross_entropy(prediction, label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(prediction, label)
    return prediction, avg_cost, acc
