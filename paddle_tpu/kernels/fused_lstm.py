"""Fused whole-sequence LSTM as a Pallas TPU kernel.

The role of the legacy fused LSTM kernels (reference:
cuda/include/hl_lstm.h:42 hl_lstm_parallel_forward — one launch computes
the whole recurrence with gate math fused) and of operators/math/
lstm_compute.*: here ONE pallas_call runs the full time loop. The grid is
(T,); TPU grids execute sequentially, so the hidden/cell state lives in
VMEM scratch across grid steps and the recurrent weight block stays
VMEM-resident for the entire sequence — the per-step HBM traffic is just
x_t in and h_t/c_t out, while the scan-based lowering reloads weights and
round-trips the carry through HBM every step.

Scope: the standard gate set (sigmoid gates, tanh cell/candidate), no
peepholes; ``ops/sequence_ops.py`` falls back to the lax.scan path
otherwise (flags.lstm_impl selects). Backward is the recompute scheme: a
plain-jax reversed scan re-derives the gates from the saved h/c sequence
(one [N,D]x[D,4D] matmul per step, the flash-attention-style
recompute-inside-backward tradeoff).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _interpret_default():
    return jax.devices()[0].platform == "cpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_lstm(xs, w, h0, c0, mask, interpret=None):
    """xs [T,N,4D] pre-projected gate inputs (bias folded in), gate slab
    order (c̃, i, f, o); w [D,4D] recurrent weights; h0/c0 [N,D]; mask
    [T,N] (1 inside the sequence). Returns (hs, cs), each [T,N,D], with
    masked steps carrying the previous state through (ragged batches)."""
    return _forward(xs, w, h0, c0, mask, interpret)[:2]


def _kernel(x_ref, w_ref, h0_ref, c0_ref, m_ref, h_out, c_out, h_scr,
            c_scr):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    h_prev = h_scr[...]
    c_prev = c_scr[...]
    g = x_ref[0].astype(jnp.float32) + jnp.dot(
        h_prev, w_ref[...].astype(jnp.float32),
        preferred_element_type=jnp.float32)        # [N, 4D] on the MXU
    D = h_prev.shape[-1]
    c_t = jnp.tanh(g[:, 0 * D:1 * D])
    i = jax.nn.sigmoid(g[:, 1 * D:2 * D])
    f = jax.nn.sigmoid(g[:, 2 * D:3 * D])
    o = jax.nn.sigmoid(g[:, 3 * D:4 * D])
    c_new = f * c_prev + i * c_t
    h_new = o * jnp.tanh(c_new)
    m = m_ref[0].astype(jnp.float32)[:, None]
    h = h_new * m + h_prev * (1.0 - m)
    c = c_new * m + c_prev * (1.0 - m)
    h_scr[...] = h
    c_scr[...] = c
    h_out[0] = h.astype(h_out.dtype)
    c_out[0] = c.astype(c_out.dtype)


def _forward(xs, w, h0, c0, mask, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    T, N, D4 = xs.shape
    D = D4 // 4
    hs, cs = pl.pallas_call(
        _kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, D4), lambda t: (t, 0, 0)),   # x_t
            pl.BlockSpec((D, D4), lambda t: (0, 0)),         # w (resident)
            pl.BlockSpec((N, D), lambda t: (0, 0)),          # h0
            pl.BlockSpec((N, D), lambda t: (0, 0)),          # c0
            pl.BlockSpec((1, N), lambda t: (t, 0)),          # mask_t
        ],
        out_specs=[
            pl.BlockSpec((1, N, D), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, N, D), lambda t: (t, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, D), xs.dtype),
            jax.ShapeDtypeStruct((T, N, D), xs.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, D), jnp.float32),
            pltpu.VMEM((N, D), jnp.float32),
        ],
        interpret=interpret,
    )(xs, w, h0, c0, mask)
    return hs, cs, (xs, w, h0, c0, mask, hs, cs)


def _fwd(xs, w, h0, c0, mask, interpret):
    hs, cs, res = _forward(xs, w, h0, c0, mask, interpret)
    return (hs, cs), res


def _bwd(interpret, res, grads):
    xs, w, h0, c0, mask, hs, cs = res
    dhs, dcs = grads
    T = xs.shape[0]
    f32 = jnp.float32
    wf = w.astype(f32)

    # previous-state sequences: h_prev[t] = hs[t-1] (h0 at t=0)
    hprev = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]], axis=0)
    cprev = jnp.concatenate([c0[None].astype(cs.dtype), cs[:-1]], axis=0)

    def step(carry, inp):
        dh_c, dc_c, dw_c = carry
        x_t, hp, cp, dh_out, dc_out, m = inp
        m = m.astype(f32)[:, None]
        hp = hp.astype(f32)
        cp = cp.astype(f32)
        # recompute the gates (the recompute-in-backward tradeoff)
        g = x_t.astype(f32) + jnp.dot(hp, wf,
                                      preferred_element_type=f32)
        D = hp.shape[-1]
        cand = jnp.tanh(g[:, 0 * D:1 * D])
        i = jax.nn.sigmoid(g[:, 1 * D:2 * D])
        f = jax.nn.sigmoid(g[:, 2 * D:3 * D])
        o = jax.nn.sigmoid(g[:, 3 * D:4 * D])
        c_new = f * cp + i * cand
        tanh_c = jnp.tanh(c_new)

        dh_t = dh_out.astype(f32) + dh_c
        dc_t = dc_out.astype(f32) + dc_c
        dh_new = dh_t * m
        dc_new = dc_t * m + dh_new * o * (1.0 - tanh_c * tanh_c)
        do = dh_new * tanh_c
        dft = dc_new * cp * f * (1.0 - f)
        dit = dc_new * cand * i * (1.0 - i)
        dcand = dc_new * i * (1.0 - cand * cand)
        dot_ = do * o * (1.0 - o)
        dg = jnp.concatenate([dcand, dit, dft, dot_], axis=-1)
        # dw accumulates in the CARRY: stacking per-step [D,4D] grads and
        # summing after would transiently cost T*D*4D memory (~420MB at
        # T=100, D=512)
        dw_acc = dw_c + jnp.dot(hp.T, dg, preferred_element_type=f32)
        dh_prev = dh_t * (1.0 - m) + jnp.dot(
            dg, wf.T, preferred_element_type=f32)
        dc_prev = dc_new * f + dc_t * (1.0 - m)
        return (dh_prev, dc_prev, dw_acc), dg

    init = (jnp.zeros_like(h0, f32), jnp.zeros_like(c0, f32),
            jnp.zeros(w.shape, f32))
    (dh0, dc0, dw), dgs = jax.lax.scan(
        step, init, (xs, hprev, cprev, dhs, dcs, mask), reverse=True)
    return (dgs.astype(xs.dtype), dw.astype(w.dtype),
            dh0.astype(h0.dtype), dc0.astype(c0.dtype),
            jnp.zeros_like(mask))


fused_lstm.defvjp(_fwd, _bwd)
