"""Pallas TPU kernels for hot ops.

Role of the reference's hand-written CUDA kernels (paddle/cuda hl_*,
operators/math/detail lstm/gru kernels, conv_cudnn): where XLA's automatic
fusion isn't enough, a Pallas kernel owns the VMEM working set explicitly.
Kernels fall back to pure-jax (or interpret mode off-TPU) so every call site
works on any backend; see /opt/skills/guides/pallas_guide.md for the
blocking rules followed here.
"""
from .flash_attention import flash_attention  # noqa: F401
from .matmul import matmul  # noqa: F401
from .paged_attention import paged_attention  # noqa: F401
