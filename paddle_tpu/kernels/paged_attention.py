"""Paged attention for the decode step as a Pallas TPU kernel.

The generation engine's fused decode step (models/transformer.decode_step)
reads each running row's K/V cache *through its block table* — and the
stock lowering does that with a gather that materialises
``[R, max_blocks, T, nh, dh]`` per layer before masking. The page-pool
layout (``[num_pages+1, T, nh, dh]`` per layer, last page = trash sink)
was shaped for this kernel instead: grid over (row blocks, kv page
blocks), the block-table indirection resolved *inside* the kernel by
scalar-prefetching the tables and letting each page's BlockSpec index
map pick its pool page — so only ``block_r * block_kv`` pages are ever
resident and the gather never exists.

Softmax is the online (running max / numerator / denominator)
decomposition accumulated in f32 VMEM scratch across the kv grid
dimension; columns past a row's position mask to ``NEG_INF`` so they
contribute exp(·)→0 exactly like the reference path's ``exp(-inf)=0``.
In decode, column 0 is always a real position (positions are >= 0), so
the running max is finite from the first tile and fully-trash later
tiles are self-correcting no-ops. Rows parked entirely on the trash
page compute attention over trash — the same garbage the gather
reference computes — and their outputs are discarded by the engine, so
parity holds on every row.

Interpret-mode capable (``interpret=not _on_tpu()``), so the parity
grid in tests/test_kernels_parity.py is tier-1-testable on CPU. The
config contract is the conv3x3/flash contract: a stale or invalid tune
pick DEGRADES to the gather reference (``resolve_block_config`` ->
None), it never fails a trace.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# candidate 0 of the search space AND the dispatch default: one row,
# one page per grid step — always legal for any pool geometry
DEFAULT_CONFIG = {"block_r": 1, "block_kv": 1}

# hard cap on block_r * block_kv: each (row, page) pair is one pallas
# input ref (the same pool array passed with a different index map), and
# an unbounded product would explode both the operand list and VMEM
MAX_PAGES_RESIDENT = 16


def population_key(max_running, max_blocks, page_tokens, num_heads,
                   head_dim, dtype="float32"):
    """The ONE encoding of a paged-attention shape key — shared by the
    engine's dispatch lookup, the tune CLI's artifact walk, and the
    space's tests, so cache signatures can never drift."""
    return {"r": int(max_running), "mb": int(max_blocks),
            "t": int(page_tokens), "nh": int(num_heads),
            "dh": int(head_dim), "dtype": str(dtype)}


def resolve_block_config(config, R, max_blocks):
    """Resolve ``(block_r, block_kv)`` for a call shape, or ``None``
    when the config cannot tile this geometry — the caller degrades to
    the gather reference. This is the single static validator: an
    invalid or stale winner-cache pick can slow a step down, never
    break one."""
    if config is None:
        return None
    cfg = dict(DEFAULT_CONFIG)
    try:
        cfg.update(dict(config))
        br = int(cfg["block_r"])
        bkv = int(cfg["block_kv"])
    except (TypeError, ValueError, KeyError):
        return None
    if br < 1 or bkv < 1 or br * bkv > MAX_PAGES_RESIDENT:
        return None
    if R % br or max_blocks % bkv:
        return None
    return br, bkv


def _on_tpu():
    from ..amp import _on_tpu as _amp_on_tpu
    return _amp_on_tpu()


def paged_attention_reference(q, k_pages, v_pages, block_tables,
                              positions):
    """The stock gather path — decode_step's attention math verbatim:
    gather ``[R, max_blocks, T, nh, dh]`` through the tables, mask
    columns past each row's position to -inf, dense softmax. The
    always-legal default the kernel is parity-gated against."""
    R, nh, dh = q.shape
    T = k_pages.shape[1]
    C = block_tables.shape[1] * T
    kc = k_pages[block_tables].reshape(R, C, nh, dh)
    vc = v_pages[block_tables].reshape(R, C, nh, dh)
    s = jnp.einsum("rhd,rchd->rhc", q, kc) * dh ** -0.5
    colmask = (jnp.arange(C, dtype=jnp.int32)[None, :]
               <= positions.astype(jnp.int32)[:, None])
    s = jnp.where(colmask[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("rhc,rchd->rhd", p, vc)


def _pa_kernel(tables_ref, pos_ref, q_ref, *refs, block_r, block_kv, T,
               scale, n_blocks):
    """One (row block, kv block) grid step: fold block_kv pages per row
    into the online-softmax scratch; emit on the last kv block."""
    from jax.experimental import pallas as pl

    nkv = block_r * block_kv
    k_refs = refs[:nkv]
    v_refs = refs[nkv:2 * nkv]
    out_ref = refs[2 * nkv]
    m_ref, num_ref, den_ref = refs[2 * nkv + 1:]
    rb = pl.program_id(0)
    b = pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        num_ref[...] = jnp.zeros_like(num_ref)
        den_ref[...] = jnp.zeros_like(den_ref)

    for i in range(block_r):
        row = rb * block_r + i
        pos = pos_ref[row]
        q = q_ref[i].astype(jnp.float32)                   # [nh, dh]
        m, num, den = m_ref[i], num_ref[i], den_ref[i]
        for j in range(block_kv):
            slot = b * block_kv + j
            k_blk = k_refs[i * block_kv + j][0].astype(jnp.float32)
            v_blk = v_refs[i * block_kv + j][0].astype(jnp.float32)
            kvpos = slot * T + jax.lax.broadcasted_iota(jnp.int32, (T,), 0)
            s = jnp.einsum("hd,thd->ht", q, k_blk) * scale   # [nh, T]
            s = jnp.where((kvpos <= pos)[None, :], s, NEG_INF)
            blk_max = jnp.max(s, axis=-1)
            new_m = jnp.maximum(m, blk_max)
            p = jnp.exp(s - new_m[:, None])
            alpha = jnp.exp(m - new_m)
            num = num * alpha[:, None] + jnp.einsum("ht,thd->hd", p, v_blk)
            den = den * alpha + jnp.sum(p, axis=-1)
            m = new_m
        m_ref[i], num_ref[i], den_ref[i] = m, num, den

    @pl.when(b == n_blocks - 1)
    def _emit():
        for i in range(block_r):
            den = jnp.maximum(den_ref[i], 1e-20)
            out_ref[i] = (num_ref[i] / den[:, None]).astype(out_ref.dtype)


def _pa_pallas(q, k_pages, v_pages, block_tables, positions, block_r,
               block_kv, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, nh, dh = q.shape
    T = k_pages.shape[1]
    MB = block_tables.shape[1]
    n_blocks = MB // block_kv
    scale = dh ** -0.5

    def page_spec(i, j):
        # the indirection: this ref's page index comes from the scalar-
        # prefetched block table, so the pool rides in whole and only
        # the addressed page is pulled into VMEM per grid step
        return pl.BlockSpec(
            (1, T, nh, dh),
            lambda rb, b, tbl, ps, i=i, j=j:
                (tbl[rb * block_r + i, b * block_kv + j], 0, 0, 0))

    pairs = [(i, j) for i in range(block_r) for j in range(block_kv)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R // block_r, n_blocks),
        in_specs=[pl.BlockSpec((block_r, nh, dh),
                               lambda rb, b, tbl, ps: (rb, 0, 0))]
        + [page_spec(i, j) for i, j in pairs]
        + [page_spec(i, j) for i, j in pairs],
        out_specs=pl.BlockSpec((block_r, nh, dh),
                               lambda rb, b, tbl, ps: (rb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_r, nh), jnp.float32),        # running max
            pltpu.VMEM((block_r, nh, dh), jnp.float32),    # numerator
            pltpu.VMEM((block_r, nh), jnp.float32),        # denominator
        ],
    )
    nkv = block_r * block_kv
    fn = pl.pallas_call(
        functools.partial(_pa_kernel, block_r=block_r, block_kv=block_kv,
                          T=T, scale=scale, n_blocks=n_blocks),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, nh, dh), q.dtype),
        interpret=interpret,
    )
    return fn(block_tables, positions.astype(jnp.int32), q,
              *([k_pages] * nkv), *([v_pages] * nkv))


def paged_attention_kwide(q, k_pages, v_pages, block_tables, positions,
                          config=None, interpret=None):
    """The speculative-verify face: ``K1`` query lanes per row against
    the SAME paged pool. ``q``: [R, K1, nh, dh] (lane i is the token fed
    at ``positions[r, i]``, K/V for all lanes already scattered);
    ``positions``: [R, K1] int32 — each lane masks its own columns, so
    lane i attends exactly the prefix a plain decode step at that
    position would. No new kernel: lanes flatten into rows
    ([R*K1, ...], tables repeated per lane) and ride the single-query
    face — the per-lane math is the decode step's verbatim, which is
    what makes greedy verification token-identical to non-speculative
    decode. ``config`` follows the decode contract: None (or a pick
    that cannot tile R*K1 rows) runs the gather reference.

    The gather path shares the K/V materialization across lanes: all
    K1 queries of a row walk the SAME block table, so the pool is
    gathered once per row ([R, C, ...]) and the lanes ride a batched
    [K1, C] attention against it — without the sharing, the verify
    step pays K1 duplicate gathers and K1 separate vector-matrix
    products, and the k-wide step costs ~K1x a plain decode step
    instead of ~1x gather + K1x (tiny) matmul FLOPs. The kernel path
    still flattens (the Pallas face is single-query per row); lanes
    repeat their tables and ride it unchanged."""
    R, K1, nh, dh = q.shape
    if resolve_block_config(config, R * K1, block_tables.shape[1]) is None:
        T = k_pages.shape[1]
        C = block_tables.shape[1] * T
        kc = k_pages[block_tables].reshape(R, C, nh, dh)
        vc = v_pages[block_tables].reshape(R, C, nh, dh)
        s = jnp.einsum("rlhd,rchd->rlhc", q, kc) * dh ** -0.5
        colmask = (jnp.arange(C, dtype=jnp.int32)[None, None, :]
                   <= positions.astype(jnp.int32)[:, :, None])
        s = jnp.where(colmask[:, :, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("rlhc,rchd->rlhd", p, vc)
    qf = q.reshape(R * K1, nh, dh)
    tables = jnp.repeat(block_tables, K1, axis=0)
    pos = positions.reshape(R * K1).astype(jnp.int32)
    out = paged_attention(qf, k_pages, v_pages, tables, pos,
                          config=config, interpret=interpret)
    return out.reshape(R, K1, nh, dh)


def paged_attention(q, k_pages, v_pages, block_tables, positions,
                    config=None, interpret=None):
    """One decode step of attention for the whole running batch.

    ``q``: [R, nh, dh] (the new token's query per row, K/V already
    scattered). ``k_pages``/``v_pages``: ONE layer's pool,
    [num_pages+1, T, nh, dh] (last page = trash). ``block_tables``:
    [R, max_blocks] int32, trash-padded. ``positions``: [R] int32 —
    columns <= position attend, the rest mask out. Returns [R, nh, dh].

    ``config`` is a paddle_tpu.tune "paged_attention" pick
    ({block_r, block_kv}); None or an invalid pick runs the gather
    reference instead (degrade, never fail)."""
    resolved = resolve_block_config(
        config if config is not None else DEFAULT_CONFIG,
        q.shape[0], block_tables.shape[1])
    if resolved is None:
        return paged_attention_reference(q, k_pages, v_pages,
                                         block_tables, positions)
    br, bkv = resolved
    if interpret is None:
        interpret = not _on_tpu()
    return _pa_pallas(q, k_pages, v_pages, block_tables, positions,
                      br, bkv, interpret)
