"""Fused whole-sequence GRU as a Pallas TPU kernel.

Companion of kernels/fused_lstm.py (see its header for the design): one
pallas_call runs the entire recurrence — sequential (T,) grid, hidden
state in VMEM scratch, both recurrent weight blocks VMEM-resident. The
role of the reference's fused GRU compute (reference:
operators/math/gru_compute.*, cuda/include/hl_gpu_gru.cuh).

Gate math (reference gru_kernel.h): with pre-projected input g [N,3D],
``u,r = sigmoid(g[:, :2D] + h_prev @ W_ur)``,
``cand = tanh(g[:, 2D:] + (r*h_prev) @ W_c)``,
``h = (1-u)*h_prev + u*cand``. Standard activations only; masked steps
carry the previous state (ragged batches). Backward recomputes the gates
from the saved h sequence in a reversed scan, weight grads accumulated in
the carry.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _interpret_default():
    return jax.devices()[0].platform == "cpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_gru(xs, w, h0, mask, interpret=None):
    """xs [T,N,3D] pre-projected (bias folded); w [D,3D] (update|reset
    recurrent block then candidate block); h0 [N,D]; mask [T,N] float.
    Returns hs [T,N,D]."""
    return _forward(xs, w, h0, mask, interpret)[0]


def _kernel(x_ref, w_ref, h0_ref, m_ref, h_out, h_scr):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)

    h_prev = h_scr[...]
    w = w_ref[...].astype(jnp.float32)
    D = h_prev.shape[-1]
    x = x_ref[0].astype(jnp.float32)
    ur = jax.nn.sigmoid(x[:, :2 * D] + jnp.dot(
        h_prev, w[:, :2 * D], preferred_element_type=jnp.float32))
    u = ur[:, :D]
    r = ur[:, D:]
    cand = jnp.tanh(x[:, 2 * D:] + jnp.dot(
        r * h_prev, w[:, 2 * D:], preferred_element_type=jnp.float32))
    h_new = (1.0 - u) * h_prev + u * cand
    m = m_ref[0].astype(jnp.float32)[:, None]
    h = h_new * m + h_prev * (1.0 - m)
    h_scr[...] = h
    h_out[0] = h.astype(h_out.dtype)


def _forward(xs, w, h0, mask, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    T, N, D3 = xs.shape
    D = D3 // 3
    hs = pl.pallas_call(
        _kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, D3), lambda t: (t, 0, 0)),
            pl.BlockSpec((D, D3), lambda t: (0, 0)),
            pl.BlockSpec((N, D), lambda t: (0, 0)),
            pl.BlockSpec((1, N), lambda t: (t, 0)),
        ],
        out_specs=pl.BlockSpec((1, N, D), lambda t: (t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((T, N, D), xs.dtype),
        scratch_shapes=[pltpu.VMEM((N, D), jnp.float32)],
        interpret=interpret,
    )(xs, w, h0, mask)
    return hs, (xs, w, h0, mask, hs)


def _fwd(xs, w, h0, mask, interpret):
    hs, res = _forward(xs, w, h0, mask, interpret)
    return hs, res


def _bwd(interpret, res, dhs):
    xs, w, h0, mask, hs = res
    f32 = jnp.float32
    wf = w.astype(f32)
    D = w.shape[0]
    w_ur = wf[:, :2 * D]
    w_c = wf[:, 2 * D:]
    hprev = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]], axis=0)

    def step(carry, inp):
        dh_c, dw_c = carry
        x_t, hp, dh_out, m = inp
        m = m.astype(f32)[:, None]
        hp = hp.astype(f32)
        x_t = x_t.astype(f32)
        ur = jax.nn.sigmoid(x_t[:, :2 * D] + jnp.dot(
            hp, w_ur, preferred_element_type=f32))
        u = ur[:, :D]
        r = ur[:, D:]
        rh = r * hp
        cand = jnp.tanh(x_t[:, 2 * D:] + jnp.dot(
            rh, w_c, preferred_element_type=f32))

        dh_t = dh_out.astype(f32) + dh_c
        dh_new = dh_t * m
        du = dh_new * (cand - hp)
        dcand = dh_new * u
        dct = dcand * (1.0 - cand * cand)        # pre-activation candidate
        drh = jnp.dot(dct, w_c.T, preferred_element_type=f32)
        dr = drh * hp
        dut = du * u * (1.0 - u)
        drt = dr * r * (1.0 - r)
        durt = jnp.concatenate([dut, drt], axis=-1)
        dx = jnp.concatenate([durt, dct], axis=-1)
        dw_ur = jnp.dot(hp.T, durt, preferred_element_type=f32)
        dw_cand = jnp.dot(rh.T, dct, preferred_element_type=f32)
        dh_prev = (dh_t * (1.0 - m) + dh_new * (1.0 - u) + drh * r
                   + jnp.dot(durt, w_ur.T, preferred_element_type=f32))
        dw_acc = dw_c + jnp.concatenate([dw_ur, dw_cand], axis=-1)
        return (dh_prev, dw_acc), dx

    init = (jnp.zeros_like(h0, f32), jnp.zeros(w.shape, f32))
    (dh0, dw), dxs = jax.lax.scan(
        step, init, (xs, hprev, dhs, mask), reverse=True)
    return (dxs.astype(xs.dtype), dw.astype(w.dtype),
            dh0.astype(h0.dtype), jnp.zeros_like(mask))


fused_gru.defvjp(_fwd, _bwd)
