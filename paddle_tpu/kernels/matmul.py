"""Blocked Pallas matmul — the fused-optimizer/FC gemm counterfactual.

reference role: operators/math/math_function.cc routing gemm to cuBLAS
(and mul_op.cc flattening to one gemm): the library picks its own tiling
per shape. XLA:TPU's dot emitter usually matches it, but the banked v5e
evidence (MFU 0.145) says the emitted schedule is not always the best
one — this kernel makes the tiling an explicit, *searchable* parameter
so paddle_tpu.tune can time (block_m, block_n, block_k) variants per
shape and bank winners, CUDA-L2 style (PAPERS.md: searched tilings
beating cuBLAS).

Schedule: grid (M/bm, N/bn, K/bk) with k innermost — TPU grids execute
sequentially, so a VMEM f32 scratch accumulates partial products across
the k steps and writes the output tile once on the last one. Default
config is the whole-problem single tile (correct everywhere, only
sensible for small operands); real tilings come from the tuner.

Dispatch: ops/math_ops.py routes ``mul`` here ONLY when the winner cache
holds a tuned pick for the (device, shape) — stock XLA stays the default
lowering, so an untuned process is bit-identical to the pre-tune build.
Backward is stock XLA (two transposed gemms via jnp.dot): the tuner
times forward+backward through jax.grad, so a winner prices the whole
step, not just the forward tile.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["matmul", "supports_matmul", "DEFAULT_CONFIG"]

DEFAULT_CONFIG = {"block_m": 0, "block_n": 0, "block_k": 0}


def supports_matmul(x_shape, y_shape, dtype):
    """True for the 2-D gemm population the kernel targets: MXU-friendly
    dims (lane axis multiple of 128, sublane multiple of 8) and floating
    operands. Everything else stays on stock XLA."""
    if len(x_shape) != 2 or len(y_shape) != 2:
        return False
    M, K = x_shape
    K2, N = y_shape
    if K != K2:
        return False
    if str(jnp.dtype(dtype)) not in ("float32", "bfloat16"):
        return False
    return M % 8 == 0 and K % 128 == 0 and N % 128 == 0


def normalize_config(config, M, N, K):
    """Resolve (bm, bn, bk) against the call shape; 0 = full extent.
    Non-dividing blocks fall back to the full extent (a stale cache
    entry must degrade to a correct schedule, never fail the call)."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(dict(config) if config else {})
    bm = int(cfg["block_m"]) or M
    bn = int(cfg["block_n"]) or N
    bk = int(cfg["block_k"]) or K
    if bm < 1 or M % bm:
        bm = M
    if bn < 1 or N % bn:
        bn = N
    if bk < 1 or K % bk:
        bk = K
    return bm, bn, bk


def _interpret_default():
    return jax.default_backend() not in ("tpu", "axon")


def _kernel(x_ref, w_ref, o_ref, acc_ref, *, n_k):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == n_k - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("out_dtype", "interpret", "config"))
def _matmul_fwd(x, w, out_dtype=None, interpret=None, config=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x.shape
    N = w.shape[1]
    out_dtype = out_dtype or x.dtype
    if interpret is None:
        interpret = _interpret_default()
    bm, bn, bk = normalize_config(config, M, N, K)
    n_k = K // bk
    return pl.pallas_call(
        functools.partial(_kernel, n_k=n_k),
        grid=(M // bm, N // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * K, transcendentals=0,
            bytes_accessed=x.size * x.dtype.itemsize
            + w.size * w.dtype.itemsize
            + M * N * jnp.dtype(out_dtype).itemsize),
        interpret=interpret,
    )(x, w)


def matmul(x, w, out_dtype=None, config=None):
    """x [M, K] @ w [K, N] -> [M, N], f32 accumulation in VMEM scratch.

    Differentiable (custom vjp; backward = stock transposed gemms).
    ``config`` is a paddle_tpu.tune "matmul" tiling; None runs the
    single-tile default."""
    frozen = tuple(sorted(dict(config).items())) if config else None
    return _matmul(x, w, out_dtype, frozen)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _matmul(x, w, out_dtype, config):
    return _matmul_fwd(x, w, out_dtype=out_dtype, config=config)


def _vjp_fwd(x, w, out_dtype, config):
    return _matmul_fwd(x, w, out_dtype=out_dtype, config=config), (x, w)


def _vjp_bwd(out_dtype, config, res, g):
    x, w = res
    gf = g.astype(jnp.float32)
    dx = jnp.dot(gf, w.astype(jnp.float32).T,
                 preferred_element_type=jnp.float32).astype(x.dtype)
    dw = jnp.dot(x.astype(jnp.float32).T, gf,
                 preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


_matmul.defvjp(_vjp_fwd, _vjp_bwd)
