"""Pallas 3x3/stride-1 convolution — the custom-kernel counterfactual for
ResNet's mid-network convs.

reference role: paddle/fluid/operators/conv_cudnn_op.cu.cc — the
reference answers a slow generic conv with a specialised kernel path
(cuDNN per-shape algorithm search). The TPU-first analog: a fused
im2col-matmul in VMEM. The 9 taps of a 3x3 kernel are 9 MXU matmuls of
(H*W, C) @ (C, O) accumulated in f32 registers — no HBM im2col buffer,
no intermediate writes between taps (the failure mode of the lax-level
shifted-einsum impl that regressed 3x end-to-end in r4: XLA materialised
tap intermediates. Here the accumulation never leaves VMEM).

Layout: NHWC activations (C on the 128-lane axis), HWIO weights — the
MXU-native conv layout. The default tiling is one grid step per image:
the whole padded feature map sits in VMEM (ResNet-50's largest 3x3 slab
is 58x58x64xbf16 = 430 KB; the largest weight block 3*3*512*512xbf16 =
4.6 MB — both comfortably inside the ~16 MB VMEM with double
buffering). Weights use a constant index map, so the pipeline keeps
them resident across the batch grid — weight-stationary.

The tiling is no longer hard-coded: ``config`` selects images per grid
step (``block_n``), the output-channel tile (``block_o``) and the grid
order (``grid_order`` — 'no' iterates batch outer / weight-stationary,
'on' iterates output-channel outer / activation-stationary). The
search space, the VMEM-footprint validity model, and the winner cache
live in ``paddle_tpu.tune`` (space "conv3x3"); this file only executes
whatever config it is handed. Accumulation stays f32 for every config —
tile shape must never move numerics.

Backward is a jax.custom_vjp: dx reuses the same kernel with spatially
rotated, io-swapped weights (a 3x3/s1 conv again); dw is the 9-tap
correlation done as einsums (one (C, N*H*W) @ (N*H*W, O) contraction
per tap — MXU-shaped, and XLA handles the cross-batch reduction well).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["conv3x3_s1_nhwc", "supports_conv3x3"]


def supports_conv3x3(w_shape, strides, paddings, dilations, groups):
    """True when (kh, kw)=(3, 3), stride 1, pad 1, no dilation/groups —
    the ResNet mid-network conv population this kernel targets."""
    return (groups == 1 and tuple(dilations) == (1, 1)
            and tuple(strides) == (1, 1) and tuple(paddings) == (1, 1)
            and tuple(w_shape[-2:]) in ((3, 3),))


def _kernel(x_ref, w_ref, o_ref, *, H, W, C, BN, BO, out_dtype):
    # x_ref: (BN, H+2, W+2, C) padded images; w_ref: (3, 3, C, BO)
    for b in range(BN):
        acc = jnp.zeros((H * W, BO), jnp.float32)
        for dy in range(3):
            for dx in range(3):
                xs = x_ref[b, dy:dy + H, dx:dx + W, :].reshape(H * W, C)
                acc += jnp.dot(xs, w_ref[dy, dx],
                               preferred_element_type=jnp.float32)
        o_ref[b] = acc.reshape(H, W, BO).astype(out_dtype)


def _interpret_default():
    # compiled Mosaic path ONLY on backends known to lower this kernel
    # (a TPU plugin may register as "tpu" or "axon"); everything else —
    # cpu tests, gpu hosts — takes the slow-but-correct interpreter.
    # The trial in bench.py relies on this: interpret mode on the real
    # chip would be silently catastrophic in a timed comparison.
    return jax.default_backend() not in ("tpu", "axon")


DEFAULT_CONFIG = {"block_n": 1, "block_o": 0, "grid_order": "no"}


def normalize_config(config, N, O):
    """Resolve a (possibly partial / frozen-tuple) config against the
    call shape; block_o=0 means the full output-channel extent. Invalid
    block sizes fall back to the default rather than failing the call —
    a stale cache entry for a changed shape must not kill training."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(dict(config) if config else {})
    bn, bo = int(cfg["block_n"]), int(cfg["block_o"]) or O
    if bn < 1 or N % bn:
        bn = 1
    if bo < 1 or O % bo:
        bo = O
    order = cfg.get("grid_order", "no")
    return bn, bo, order if order in ("no", "on") else "no"


@functools.partial(jax.jit,
                   static_argnames=("out_dtype", "interpret", "config"))
def _conv3x3_fwd(x, w, out_dtype=None, interpret=None, config=None):
    """x: (N, H, W, C); w: (3, 3, C, O) -> (N, H, W, O)."""
    N, H, W, C = x.shape
    O = w.shape[3]
    out_dtype = out_dtype or x.dtype
    if interpret is None:
        interpret = _interpret_default()
    BN, BO, order = normalize_config(config, N, O)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    kern = functools.partial(_kernel, H=H, W=W, C=C, BN=BN, BO=BO,
                             out_dtype=out_dtype)
    flops = 2 * N * H * W * C * O * 9
    if order == "no":
        # batch outer: the weight tile's index map is constant along the
        # inner axis only when output channels iterate fastest — with
        # BO == O this is the original weight-stationary schedule
        grid = (N // BN, O // BO)
        x_map = lambda n, o: (n, 0, 0, 0)
        w_map = lambda n, o: (0, 0, 0, o)
        o_map = lambda n, o: (n, 0, 0, o)
    else:
        # output-channel outer: the activation tile stays resident while
        # one weight block streams the whole batch (activation-stationary
        # — wins when weights dwarf the feature map)
        grid = (O // BO, N // BN)
        x_map = lambda o, n: (n, 0, 0, 0)
        w_map = lambda o, n: (0, 0, 0, o)
        o_map = lambda o, n: (n, 0, 0, o)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BN, H + 2, W + 2, C), x_map),
            pl.BlockSpec((3, 3, C, BO), w_map),
        ],
        out_specs=pl.BlockSpec((BN, H, W, BO), o_map),
        out_shape=jax.ShapeDtypeStruct((N, H, W, O), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops, transcendentals=0,
            bytes_accessed=x.size * x.dtype.itemsize
            + w.size * w.dtype.itemsize
            + N * H * W * O * jnp.dtype(out_dtype).itemsize),
        interpret=interpret,
    )(xp, w)


def conv3x3_s1_nhwc(x, w, out_dtype=None, config=None):
    """3x3/s1/p1 convolution, NHWC x HWIO -> NHWC, f32 accumulation.

    Differentiable (custom vjp); on backends other than tpu/axon the
    kernel runs in pallas interpret mode, so tests and CPU/GPU
    fallbacks stay correct (slowly) while TPU gets compiled Mosaic.
    ``config`` is a paddle_tpu.tune "conv3x3" tiling (dict or frozen
    item-tuple); None runs the default single-image weight-stationary
    schedule."""
    frozen = tuple(sorted(dict(config).items())) if config else None
    return _conv3x3(x, w, out_dtype, frozen)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _conv3x3(x, w, out_dtype, config):
    return _conv3x3_fwd(x, w, out_dtype=out_dtype, config=config)


def _vjp_fwd(x, w, out_dtype, config):
    return _conv3x3_fwd(x, w, out_dtype=out_dtype, config=config), (x, w)


def _vjp_bwd(out_dtype, config, res, g):
    x, w = res
    # dx: full-correlation of g with the rotated kernel — another
    # 3x3/s1/p1 conv, so the pallas kernel serves its own backward.
    # The forward's tiling config does not transfer (output channels
    # swap roles with input channels), so the backward runs the default
    # schedule — the tuner times forward+backward together through
    # jax.grad, so a winner already prices this.
    w_rot = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2))   # (3,3,O,C)
    dx = _conv3x3_fwd(g.astype(x.dtype), w_rot, out_dtype=None)
    # dw[dy,dx,c,o] = sum_{n,h,w} xpad[n,h+dy,w+dx,c] g[n,h,w,o]
    N, H, W, C = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = []
    for dy in range(3):
        row = []
        for dxx in range(3):
            patch = xp[:, dy:dy + H, dxx:dxx + W, :]
            row.append(jnp.einsum("nhwc,nhwo->co", patch, g,
                                  preferred_element_type=jnp.float32))
        taps.append(jnp.stack(row))
    dw = jnp.stack(taps).astype(w.dtype)                 # (3,3,C,O)
    return dx.astype(x.dtype), dw


_conv3x3.defvjp(_vjp_fwd, _vjp_bwd)
