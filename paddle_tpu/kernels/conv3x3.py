"""Pallas 3x3/stride-1 convolution — the custom-kernel counterfactual for
ResNet's mid-network convs.

reference role: paddle/fluid/operators/conv_cudnn_op.cu.cc — the
reference answers a slow generic conv with a specialised kernel path
(cuDNN per-shape algorithm search). The TPU-first analog: a fused
im2col-matmul in VMEM. The 9 taps of a 3x3 kernel are 9 MXU matmuls of
(H*W, C) @ (C, O) accumulated in f32 registers — no HBM im2col buffer,
no intermediate writes between taps (the failure mode of the lax-level
shifted-einsum impl that regressed 3x end-to-end in r4: XLA materialised
tap intermediates. Here the accumulation never leaves VMEM).

Layout: NHWC activations (C on the 128-lane axis), HWIO weights — the
MXU-native conv layout. One grid step per image: the whole padded
feature map sits in VMEM (ResNet-50's largest 3x3 slab is
58x58x64xbf16 = 430 KB; the largest weight block 3*3*512*512xbf16 =
4.6 MB — both comfortably inside the ~16 MB VMEM with double
buffering). Weights use a constant index map, so the pipeline keeps
them resident across the batch grid — weight-stationary.

Backward is a jax.custom_vjp: dx reuses the same kernel with spatially
rotated, io-swapped weights (a 3x3/s1 conv again); dw is the 9-tap
correlation done as einsums (one (C, N*H*W) @ (N*H*W, O) contraction
per tap — MXU-shaped, and XLA handles the cross-batch reduction well).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["conv3x3_s1_nhwc", "supports_conv3x3"]


def supports_conv3x3(w_shape, strides, paddings, dilations, groups):
    """True when (kh, kw)=(3, 3), stride 1, pad 1, no dilation/groups —
    the ResNet mid-network conv population this kernel targets."""
    return (groups == 1 and tuple(dilations) == (1, 1)
            and tuple(strides) == (1, 1) and tuple(paddings) == (1, 1)
            and tuple(w_shape[-2:]) in ((3, 3),))


def _kernel(x_ref, w_ref, o_ref, *, H, W, C, O, out_dtype):
    # x_ref: (1, H+2, W+2, C) padded image; w_ref: (3, 3, C, O)
    acc = jnp.zeros((H * W, O), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            xs = x_ref[0, dy:dy + H, dx:dx + W, :].reshape(H * W, C)
            acc += jnp.dot(xs, w_ref[dy, dx],
                           preferred_element_type=jnp.float32)
    o_ref[0] = acc.reshape(H, W, O).astype(out_dtype)


def _interpret_default():
    # compiled Mosaic path ONLY on backends known to lower this kernel
    # (a TPU plugin may register as "tpu" or "axon"); everything else —
    # cpu tests, gpu hosts — takes the slow-but-correct interpreter.
    # The trial in bench.py relies on this: interpret mode on the real
    # chip would be silently catastrophic in a timed comparison.
    return jax.default_backend() not in ("tpu", "axon")


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def _conv3x3_fwd(x, w, out_dtype=None, interpret=None):
    """x: (N, H, W, C); w: (3, 3, C, O) -> (N, H, W, O)."""
    N, H, W, C = x.shape
    O = w.shape[3]
    out_dtype = out_dtype or x.dtype
    if interpret is None:
        interpret = _interpret_default()
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    kern = functools.partial(_kernel, H=H, W=W, C=C, O=O,
                             out_dtype=out_dtype)
    flops = 2 * N * H * W * C * O * 9
    return pl.pallas_call(
        kern,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, H + 2, W + 2, C), lambda n: (n, 0, 0, 0)),
            # constant index map: weights stay VMEM-resident across the
            # batch grid (weight-stationary)
            pl.BlockSpec((3, 3, C, O), lambda n: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, W, O), lambda n: (n, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N, H, W, O), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops, transcendentals=0,
            bytes_accessed=x.size * x.dtype.itemsize
            + w.size * w.dtype.itemsize
            + N * H * W * O * jnp.dtype(out_dtype).itemsize),
        interpret=interpret,
    )(xp, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv3x3_s1_nhwc(x, w, out_dtype=None):
    """3x3/s1/p1 convolution, NHWC x HWIO -> NHWC, f32 accumulation.

    Differentiable (custom vjp); on backends other than tpu/axon the
    kernel runs in pallas interpret mode, so tests and CPU/GPU
    fallbacks stay correct (slowly) while TPU gets compiled Mosaic."""
    return _conv3x3_fwd(x, w, out_dtype=out_dtype)


def _vjp_fwd(x, w, out_dtype):
    return _conv3x3_fwd(x, w, out_dtype=out_dtype), (x, w)


def _vjp_bwd(out_dtype, res, g):
    x, w = res
    # dx: full-correlation of g with the rotated kernel — another
    # 3x3/s1/p1 conv, so the pallas kernel serves its own backward
    w_rot = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2))   # (3,3,O,C)
    dx = _conv3x3_fwd(g.astype(x.dtype), w_rot, out_dtype=None)
    # dw[dy,dx,c,o] = sum_{n,h,w} xpad[n,h+dy,w+dx,c] g[n,h,w,o]
    N, H, W, C = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = []
    for dy in range(3):
        row = []
        for dxx in range(3):
            patch = xp[:, dy:dy + H, dxx:dxx + W, :]
            row.append(jnp.einsum("nhwc,nhwo->co", patch, g,
                                  preferred_element_type=jnp.float32))
        taps.append(jnp.stack(row))
    dw = jnp.stack(taps).astype(w.dtype)                 # (3,3,C,O)
    return dx.astype(x.dtype), dw


conv3x3_s1_nhwc.defvjp(_vjp_fwd, _vjp_bwd)
