"""Flash attention (forward) as a Pallas TPU kernel.

Streams k/v blocks through VMEM against a resident q block, maintaining the
online-softmax (running max / numerator / denominator) decomposition, so the
[S, S] score matrix never materialises in HBM — the single-chip sibling of
parallel/ring.py's cross-chip ring (same math, different memory wall).

Backward is recompute-based (jax.custom_vjp over the dense reference
implementation) — standard flash practice: recompute beats storing S²
activations; a dedicated Pallas backward is a later optimisation.

No reference equivalent (attention postdates the 2018 codebase); this is a
capability the TPU build adds, used by nets.scaled_dot_product_attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

BLOCK_Q = 128
BLOCK_K = 128


def _dense_reference(q, k, v, causal, scale):
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale, block_k,
               seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)              # [BLOCK_Q, D]
    bq, d = q.shape
    n_k = seq_len // block_k

    def body(ki, acc):
        m, num, den = acc
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                      (bq, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, -1e30)
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m[:, None])
        alpha = jnp.exp(m - new_m)
        num = num * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        den = den * alpha + jnp.sum(p, axis=-1)
        return new_m, num, den

    m0 = jnp.full((bq,), -1e30, jnp.float32)
    num0 = jnp.zeros((bq, d), jnp.float32)
    den0 = jnp.zeros((bq,), jnp.float32)
    if causal and bq == block_k:
        # blocks strictly above the diagonal contribute nothing
        n_k = qi + 1
    m, num, den = jax.lax.fori_loop(0, n_k, body, (m0, num0, den0))
    o_ref[0] = (num / jnp.maximum(den[:, None], 1e-20)).astype(o_ref.dtype)


def _fa_forward(q3, k3, v3, causal, scale, interpret):
    """q3/k3/v3: [BH, S, D] -> [BH, S, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    BH, S, D = q3.shape
    block_q = min(BLOCK_Q, S)
    block_k = min(BLOCK_K, S)
    kernel = functools.partial(_fa_kernel, causal=causal, scale=scale,
                               block_k=block_k, seq_len=S)
    return pl.pallas_call(
        kernel,
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
        interpret=interpret,
    )(q3, k3, v3)


def _on_tpu():
    # shared accelerator check (tunnelled PJRT plugins report their own
    # platform name; anything non-cpu runs the compiled Pallas path)
    from ..amp import _on_tpu as _amp_on_tpu
    return _amp_on_tpu()


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q3, k3, v3, causal, scale):
    return _fa_forward(q3, k3, v3, causal, scale, interpret=not _on_tpu())


def _flash_fwd(q3, k3, v3, causal, scale):
    return _flash(q3, k3, v3, causal, scale), (q3, k3, v3)


def _flash_bwd(causal, scale, res, g):
    q3, k3, v3 = res
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_reference(q, k, v, causal, scale),
        q3, k3, v3)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None):
    """q/k/v: [batch, seq, heads, dim] -> [batch, seq, heads, dim].

    Pallas streamed-softmax forward on TPU (interpret mode elsewhere),
    recompute backward. Sequence length must divide by the 128-wide block
    (or be <=128); ragged batches bucket to these sizes upstream."""
    B, S, H, D = q.shape
    if S > BLOCK_Q and S % BLOCK_Q != 0:
        # off-size sequence: dense fallback keeps semantics
        scale_ = scale if scale is not None else D ** -0.5
        merged = _dense_reference(
            q.transpose(0, 2, 1, 3).reshape(B * H, S, D),
            k.transpose(0, 2, 1, 3).reshape(B * H, S, D),
            v.transpose(0, 2, 1, 3).reshape(B * H, S, D), causal, scale_)
        return merged.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    scale = scale if scale is not None else D ** -0.5
    q3 = q.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    o3 = _flash(q3, k3, v3, causal, scale)
    return o3.reshape(B, H, S, D).transpose(0, 2, 1, 3)
