"""Flash attention (forward + backward) as Pallas TPU kernels.

Forward streams k/v blocks through VMEM against a resident q block,
maintaining the online-softmax (running max / numerator / denominator)
decomposition, and emits the per-row logsumexp — so the [S, S] score matrix
never materialises in HBM. Backward is the FlashAttention-2 recompute
scheme as two Pallas kernels: a dK/dV kernel (grid over k blocks, loop over
q blocks) and a dQ kernel (grid over q blocks, loop over k blocks); every
score/probability tile lives only as a [block_q, block_k] VMEM tile.

Ragged sequence lengths (S % 128 != 0) are handled by padding to the block
size and masking padded k positions inside the kernels; padded q rows are
sliced off (and contribute exactly zero to dK/dV because their dO rows are
zero-padded).

The logsumexp output is what lets parallel/ring.py chain per-ring-step
flash calls with the numerically exact merge
``o = (o_a * exp(lse_a - lse) + o_b * exp(lse_b - lse))`` — gradients flow
through both o and lse (the dlse term folds into the backward's delta).

No reference equivalent (attention postdates the 2018 codebase); this is a
capability the TPU build adds, used by nets.scaled_dot_product_attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

BLOCK_Q = 128
BLOCK_K = 128
NEG_INF = -1e30

DEFAULT_CONFIG = {"block_q": BLOCK_Q, "block_k": BLOCK_K}


def _blocks_from_config(config, Sq, Sk):
    """Resolve (block_q, block_k) for the call shape: configured blocks
    (a paddle_tpu.tune "flash_attention" pick) clamp to the sequence
    lengths and fall back to the 128 defaults when they don't divide the
    padded sequence — a stale cache entry must degrade, not fail."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(dict(config) if config else {})
    bq = min(int(cfg["block_q"]), max(Sq, 1))
    bk = min(int(cfg["block_k"]), max(Sk, 1))
    if bq < 1 or bk < 1:
        bq, bk = min(BLOCK_Q, Sq), min(BLOCK_K, Sk)
    return bq, bk


def _dense_reference(q, k, v, causal, scale):
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


# ---------------------------------------------------------------------------
# forward kernel: one q block vs streamed k/v blocks -> o block + lse rows

def _masked_scores(q, k_blk, q_start, k_start, *, causal, scale, valid_len,
                   kv_len):
    """Scaled q@k^T tile with the causal and padded-k masks applied — the
    single source of masking truth shared by forward and both backward
    kernels (they must never disagree)."""
    s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
    bq, bk = s.shape
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if causal:
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    if valid_len < kv_len:
        s = jnp.where(kpos < valid_len, s, NEG_INF)
    return s




def _fa_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, causal, scale, block_k,
               kv_len, valid_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)              # [BLOCK_Q, D]
    bq, d = q.shape
    n_k = kv_len // block_k

    def body(ki, acc):
        m, num, den = acc
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = _masked_scores(q, k_blk, qi * bq, ki * block_k, causal=causal,
                           scale=scale, valid_len=valid_len, kv_len=kv_len)
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m[:, None])
        alpha = jnp.exp(m - new_m)
        num = num * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        den = den * alpha + jnp.sum(p, axis=-1)
        return new_m, num, den

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    num0 = jnp.zeros((bq, d), jnp.float32)
    den0 = jnp.zeros((bq,), jnp.float32)
    if causal and bq == block_k:
        # blocks strictly above the diagonal contribute nothing
        n_k = qi + 1
    m, num, den = jax.lax.fori_loop(0, n_k, body, (m0, num0, den0))
    den_safe = jnp.maximum(den, 1e-20)
    o_ref[0] = (num / den_safe[:, None]).astype(o_ref.dtype)
    l_ref[0] = (m + jnp.log(den_safe)).astype(jnp.float32)


def _fa_forward(q3, k3, v3, causal, scale, valid_len, interpret,
                config=None):
    """q3 [BH, Sq, D], k3/v3 [BH, Sk, D] -> (o [BH, Sq, D], lse [BH, Sq]).
    Sq may differ from Sk (ring-attention block chaining); causal requires
    Sq == Sk (aligned positions)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    block_q, block_k = _blocks_from_config(config, Sq, Sk)
    kernel = functools.partial(_fa_kernel, causal=causal, scale=scale,
                               block_k=block_k, kv_len=Sk,
                               valid_len=valid_len)
    return pl.pallas_call(
        kernel,
        grid=(BH, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Sq), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2 recompute scheme)
#
# With p = exp(s - lse):  dv = p^T dO;  dp = dO v^T;
# ds = p * (dp - delta) * scale where delta = rowsum(dO * o) - dlse;
# dq = ds k;  dk = ds^T q.  All tiles [block_q, block_k] in VMEM.


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, dl_ref,
                       dk_ref, dv_ref, *, causal, scale, block_q,
                       q_len, kv_len, valid_len):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)          # [BLOCK_K, D]
    v_blk = v_ref[0].astype(jnp.float32)
    bk, d = k_blk.shape
    n_q = q_len // block_q

    def body(qi, acc):
        dk, dv = acc
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = l_ref[0, pl.ds(qi * block_q, block_q)]
        delta = dl_ref[0, pl.ds(qi * block_q, block_q)]
        s = _masked_scores(q, k_blk, qi * block_q, ki * bk, causal=causal,
                           scale=scale, valid_len=valid_len, kv_len=kv_len)
        p = jnp.exp(s - lse[:, None])
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    start = (ki * bk) // block_q if (causal and bk == block_q) else 0
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, dl_ref,
                      dq_ref, *, causal, scale, block_k, kv_len,
                      valid_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)              # [BLOCK_Q, D]
    do = do_ref[0].astype(jnp.float32)
    lse = l_ref[0]
    delta = dl_ref[0]
    bq, d = q.shape
    n_k = kv_len // block_k

    def body(ki, dq):
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = _masked_scores(q, k_blk, qi * bq, ki * block_k, causal=causal,
                           scale=scale, valid_len=valid_len, kv_len=kv_len)
        p = jnp.exp(s - lse[:, None])
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal and bq == block_k:
        n_k = qi + 1
    dq = jax.lax.fori_loop(0, n_k, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _fa_backward(q3, k3, v3, do3, lse, delta, causal, scale, valid_len,
                 interpret, config=None):
    from jax.experimental import pallas as pl

    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    block_q, block_k = _blocks_from_config(config, Sq, Sk)
    dkv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, q_len=Sq, kv_len=Sk,
                          valid_len=valid_len),
        grid=(BH, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0)),     # q
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),  # k blk
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),  # v blk
            pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0)),     # do
            pl.BlockSpec((1, Sq), lambda b, i: (b, 0)),           # lse
            pl.BlockSpec((1, Sq), lambda b, i: (b, 0)),           # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k3.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v3.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, causal=causal, scale=scale,
                          block_k=block_k, kv_len=Sk, valid_len=valid_len),
        grid=(BH, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),  # q blk
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),     # k
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),     # v
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),  # do blk
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),      # lse
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),      # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dkv[0], dkv[1]


# ---------------------------------------------------------------------------


def _on_tpu():
    # shared accelerator check (tunnelled PJRT plugins report their own
    # platform name; anything non-cpu runs the compiled Pallas path)
    from ..amp import _on_tpu as _amp_on_tpu
    return _amp_on_tpu()


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q3, k3, v3, causal, scale, valid_len, config=None):
    """[BH, S, D] x3 -> (o [BH, S, D], lse [BH, S]); S % block == 0."""
    return _fa_forward(q3, k3, v3, causal, scale, valid_len,
                       interpret=not _on_tpu(), config=config)


def _flash_fwd(q3, k3, v3, causal, scale, valid_len, config=None):
    o, lse = _flash(q3, k3, v3, causal, scale, valid_len, config)
    return (o, lse), (q3, k3, v3, o, lse)


def _flash_bwd(causal, scale, valid_len, config, res, cots):
    q3, k3, v3, o, lse = res
    do3, dlse = cots
    # delta folds the lse cotangent: ds = p * (dp - rowsum(do*o) + dlse)
    delta = jnp.einsum("bsd,bsd->bs", do3.astype(jnp.float32),
                       o.astype(jnp.float32))
    if dlse is not None:
        delta = delta - dlse
    dq, dk, dv = _fa_backward(q3, k3, v3, do3, lse, delta, causal, scale,
                              valid_len, interpret=not _on_tpu(),
                              config=config)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pad_seq(x, S_pad):
    B, S, H, D = x.shape
    if S == S_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, S_pad - S), (0, 0), (0, 0)))


def flash_attention_with_lse(q, k, v, causal=False, scale=None,
                             config=None):
    """q/k/v: [batch, seq, heads, dim] -> (out [B, S, H, D], lse [B, H, S]).

    Any sequence length: S pads up to the block width internally; padded
    k positions are masked inside the kernels and padded q rows sliced off.
    The lse output makes per-block results mergeable (ring attention).
    ``config`` is a paddle_tpu.tune "flash_attention" pick
    ({block_q, block_k}); None keeps the 128x128 defaults.
    """
    B, S, H, D = q.shape
    Sk = k.shape[1]
    if causal and S != Sk:
        raise ValueError("causal flash attention needs q/k aligned lengths")
    scale = scale if scale is not None else D ** -0.5
    bq, bk = _blocks_from_config(config, S, Sk)
    S_pad = ((S + bq - 1) // bq) * bq
    Sk_pad = ((Sk + bk - 1) // bk) * bk
    frozen = tuple(sorted(dict(config).items())) if config else None
    q3 = _pad_seq(q, S_pad).transpose(0, 2, 1, 3).reshape(B * H, S_pad, D)
    k3 = _pad_seq(k, Sk_pad).transpose(0, 2, 1, 3).reshape(B * H, Sk_pad, D)
    v3 = _pad_seq(v, Sk_pad).transpose(0, 2, 1, 3).reshape(B * H, Sk_pad, D)
    o3, lse = _flash(q3, k3, v3, causal, scale, Sk, frozen)
    o = o3.reshape(B, H, S_pad, D)[:, :, :S].transpose(0, 2, 1, 3)
    return o, lse.reshape(B, H, S_pad)[:, :, :S]


def flash_attention(q, k, v, causal=False, scale=None, config=None):
    """q/k/v: [batch, seq, heads, dim] -> [batch, seq, heads, dim].

    Pallas streamed-softmax forward on TPU (interpret mode elsewhere),
    Pallas recompute backward (dq/dk/dv kernels) — no [S, S] buffer in
    either direction, any sequence length."""
    return flash_attention_with_lse(q, k, v, causal, scale, config)[0]
