"""Ops tail: hierarchical sigmoid, factorization machine, multiplex,
spatial pyramid pooling, max-pool-with-index / unpool, 2-D (MD) LSTM,
log-uniform sampler.

reference: paddle/gserver/layers/HierarchicalSigmoidLayer.cpp +
fluid operators/hierarchical_sigmoid_op (MatrixBitCodeFunctor),
gserver/layers/FactorizationMachineLayer.cpp, operators/multiplex_op.cc,
operators/spp_op.cc, operators/unpool_op.cc + math/unpooling.cc,
gserver/layers/MDLstmLayer.cpp, operators/math/sampler.h (LogUniform).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.executor import raw_data
from ..core.registry import register_op


# ---------------------------------------------------------------------------
# hierarchical sigmoid — complete-binary-tree coded softmax

def _tree_codes(num_classes):
    """Static (path_node_index, path_bit, path_mask) tables for every class
    under the complete-binary-tree coding of the reference's SimpleCode:
    c = class + num_classes; length = findLastSet(c) - 1;
    node(bit) = (c >> (length - 1 - bit)) - 1;
    bit(bit)  = (c >> (length - 1 - bit - 1)) & 1  (child direction).
    Padded to the max code length with mask=0."""
    import numpy as np
    max_len = int(math.floor(math.log2(2 * num_classes - 1)))
    nodes = np.zeros((num_classes, max_len), np.int32)
    bits = np.zeros((num_classes, max_len), np.float32)
    mask = np.zeros((num_classes, max_len), np.float32)
    for c in range(num_classes):
        code = c + num_classes
        length = code.bit_length() - 1
        for i in range(length):
            nodes[c, i] = (code >> (length - i)) - 1
            bits[c, i] = float((code >> (length - i - 1)) & 1)
            mask[c, i] = 1.0
    return jnp.asarray(nodes), jnp.asarray(bits), jnp.asarray(mask)


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid(ctx):
    """Cost[n] = -sum_i log sigmoid((1-2*bit_i) * (x_n . w_node_i + b_node_i))
    over the label's root-to-leaf path. The code tables are static arrays
    (gathered by traced labels), so the whole op is one batched gather +
    matmul — no per-sample host loop.
    reference: operators/hierarchical_sigmoid_op.h HierarchicalSigmoidKernel
    + gserver/layers/HierarchicalSigmoidLayer.cpp."""
    x = raw_data(ctx.input("X"))                         # [N, D]
    w = raw_data(ctx.input("W"))                         # [C-1, D]
    label = raw_data(ctx.input("Label")).reshape(-1).astype(jnp.int32)
    bias = ctx.input("Bias")
    num_classes = int(ctx.attr("num_classes"))
    nodes, bits, mask = _tree_codes(num_classes)
    n_idx = jnp.take(nodes, label, axis=0)               # [N, L]
    n_bit = jnp.take(bits, label, axis=0)
    n_mask = jnp.take(mask, label, axis=0)
    w_path = jnp.take(w, n_idx, axis=0)                  # [N, L, D]
    logits = jnp.einsum("nd,nld->nl", x, w_path)
    if bias is not None:
        logits = logits + jnp.take(raw_data(bias).reshape(-1), n_idx)
    sign = 1.0 - 2.0 * n_bit
    # -log sigmoid(sign * logit) = softplus(-sign * logit)
    cost = jnp.sum(jax.nn.softplus(-sign * logits) * n_mask, axis=1)
    ctx.set_output("Out", cost[:, None])


@register_op("factorization_machine")
def factorization_machine(ctx):
    """Second-order FM term: 0.5 * sum_k ((x V)_k^2 - (x^2 V^2)_k).
    reference: gserver/layers/FactorizationMachineLayer.cpp (latentVectors_
    V [D, K])."""
    x = raw_data(ctx.input("X"))                         # [N, D]
    v = raw_data(ctx.input("V"))                         # [D, K]
    xv = jnp.dot(x, v)
    x2v2 = jnp.dot(x * x, v * v)
    out = 0.5 * jnp.sum(xv * xv - x2v2, axis=1, keepdims=True)
    ctx.set_output("Out", out)


@register_op("multiplex")
def multiplex(ctx):
    """Out[i] = Ins[ids[i]][i]: per-row selection among K candidates.
    reference: operators/multiplex_op.cc."""
    ids = raw_data(ctx.input("Ids")).reshape(-1).astype(jnp.int32)
    ins = [raw_data(v) for v in ctx.inputs("X")]
    stacked = jnp.stack(ins)                             # [K, N, ...]
    out = stacked[ids, jnp.arange(stacked.shape[1])]
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# spatial pyramid pooling (reference: operators/spp_op.cc): per level l,
# adaptive-pool X into 2^l x 2^l bins, flatten, concat over levels.

def _adaptive_pool2d(x, bins, pool_type):
    N, C, H, W = x.shape
    outs = []
    for by in range(bins):
        y0 = (by * H) // bins
        y1 = max(((by + 1) * H + bins - 1) // bins, y0 + 1)
        row = []
        for bx in range(bins):
            x0 = (bx * W) // bins
            x1 = max(((bx + 1) * W + bins - 1) // bins, x0 + 1)
            win = x[:, :, y0:y1, x0:x1]
            r = (jnp.max(win, axis=(2, 3)) if pool_type == "max"
                 else jnp.mean(win, axis=(2, 3)))
            row.append(r)
        outs.append(jnp.stack(row, axis=-1))             # [N, C, bins]
    return jnp.stack(outs, axis=-2)                      # [N, C, bins, bins]


@register_op("spp")
def spp(ctx):
    x = raw_data(ctx.input("X"))
    levels = int(ctx.attr("pyramid_height"))
    ptype = str(ctx.attr("pooling_type", "max"))
    feats = []
    for l in range(levels):
        pooled = _adaptive_pool2d(x, 2 ** l, ptype)
        feats.append(pooled.reshape(x.shape[0], -1))
    ctx.set_output("Out", jnp.concatenate(feats, axis=1))


# ---------------------------------------------------------------------------
# max pool with index + unpool (reference: operators/max_pool_with_index_op,
# unpool_op.cc + math/unpooling.cc — indices are flat positions within each
# [H, W] map)

@register_op("max_pool2d_with_index")
def max_pool2d_with_index(ctx):
    x = raw_data(ctx.input("X"))
    N, C, H, W = x.shape
    ks = ctx.attr("ksize", [2, 2])
    st = ctx.attr("strides", ks)
    pd = ctx.attr("paddings", [0, 0])
    kh, kw = int(ks[0]), int(ks[1])
    sh, sw = int(st[0]), int(st[1])
    ph, pw = int(pd[0]), int(pd[1])
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=neg)
    OH = (H + 2 * ph - kh) // sh + 1
    OW = (W + 2 * pw - kw) // sw + 1
    # window positions as [OH*OW, kh*kw] flat indices into the padded map,
    # then gather and argmax — index arithmetic maps back to unpadded H*W
    oy = jnp.arange(OH) * sh
    ox = jnp.arange(OW) * sw
    wy = jnp.arange(kh)
    wx = jnp.arange(kw)
    ys = oy[:, None, None, None] + wy[None, None, :, None]  # [OH,1,kh,1]
    xs = ox[None, :, None, None] + wx[None, None, None, :]  # [1,OW,1,kw]
    ys = jnp.broadcast_to(ys, (OH, OW, kh, kw))
    xs = jnp.broadcast_to(xs, (OH, OW, kh, kw))
    flat = (ys * (W + 2 * pw) + xs).reshape(OH * OW, kh * kw)
    xp_flat = xp.reshape(N, C, -1)
    wins = jnp.take(xp_flat, flat, axis=2)               # [N,C,OH*OW,khkw]
    arg = jnp.argmax(wins, axis=3)
    out = jnp.max(wins, axis=3).reshape(N, C, OH, OW)
    # winner position in padded coords -> unpadded flat H*W index
    win_flat = jnp.take_along_axis(
        jnp.broadcast_to(flat[None, None], wins.shape).astype(jnp.int32),
        arg[..., None].astype(jnp.int32), axis=3)[..., 0]
    py = win_flat // (W + 2 * pw) - ph
    px = win_flat % (W + 2 * pw) - pw
    idx = (py * W + px).reshape(N, C, OH, OW)
    ctx.set_output("Out", out)
    ctx.set_output("Mask", idx.astype(jnp.int32))


@register_op("max_pool3d_with_index")
def max_pool3d_with_index(ctx):
    """3d analog of max_pool2d_with_index above — indices are flat
    positions within each [D, H, W] volume. reference:
    operators/pool_with_index_op.cc (max_pool3d_with_index registration)
    + math/pooling.cc MaxPool3dWithIndexFunctor."""
    x = raw_data(ctx.input("X"))
    N, C, D, H, W = x.shape
    ks = [int(k) for k in ctx.attr("ksize", [2, 2, 2])]
    st = [int(s) for s in ctx.attr("strides", ks)]
    pd = [int(p) for p in ctx.attr("paddings", [0, 0, 0])]
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0)) + tuple((p, p) for p in pd),
                 constant_values=neg)
    od = [(dim + 2 * pd[i] - ks[i]) // st[i] + 1
          for i, dim in enumerate((D, H, W))]
    pD, pH, pW = (D + 2 * pd[0], H + 2 * pd[1], W + 2 * pd[2])
    # window origin grids and intra-window offsets -> flat padded indices
    oz = (jnp.arange(od[0]) * st[0])[:, None, None, None, None, None]
    oy = (jnp.arange(od[1]) * st[1])[None, :, None, None, None, None]
    ox = (jnp.arange(od[2]) * st[2])[None, None, :, None, None, None]
    wz = jnp.arange(ks[0])[None, None, None, :, None, None]
    wy = jnp.arange(ks[1])[None, None, None, None, :, None]
    wx = jnp.arange(ks[2])[None, None, None, None, None, :]
    zs = jnp.broadcast_to(oz + wz, tuple(od) + tuple(ks))
    ys = jnp.broadcast_to(oy + wy, tuple(od) + tuple(ks))
    xs = jnp.broadcast_to(ox + wx, tuple(od) + tuple(ks))
    flat = ((zs * pH + ys) * pW + xs).reshape(
        od[0] * od[1] * od[2], ks[0] * ks[1] * ks[2])
    xp_flat = xp.reshape(N, C, -1)
    wins = jnp.take(xp_flat, flat, axis=2)
    arg = jnp.argmax(wins, axis=3)
    out = jnp.max(wins, axis=3).reshape(N, C, *od)
    win_flat = jnp.take_along_axis(
        jnp.broadcast_to(flat[None, None], wins.shape).astype(jnp.int32),
        arg[..., None].astype(jnp.int32), axis=3)[..., 0]
    pz = win_flat // (pH * pW) - pd[0]
    py = (win_flat // pW) % pH - pd[1]
    px = win_flat % pW - pd[2]
    idx = ((pz * H + py) * W + px).reshape(N, C, *od)
    ctx.set_output("Out", out)
    ctx.set_output("Mask", idx.astype(jnp.int32))


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx):
    """reference: operators/bilinear_tensor_product_op.cc —
    out[b, k] = x[b] @ W[k] @ y[b] + bias[k]; X [B, M], Y [B, N],
    Weight [K, M, N], Bias [1, K]. One einsum: the MXU sees a batched
    matmul instead of the reference's per-output-channel GEMM loop."""
    x = raw_data(ctx.input("X"))
    y = raw_data(ctx.input("Y"))
    w = raw_data(ctx.input("Weight"))
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    if ctx.has_input("Bias"):
        out = out + raw_data(ctx.input("Bias")).reshape(1, -1)
    ctx.set_output("Out", out)


@register_op("unpool")
def unpool(ctx):
    """Scatter pooled activations back to the positions recorded by
    max_pool2d_with_index. reference: operators/unpool_op.cc."""
    x = raw_data(ctx.input("X"))                         # [N,C,h,w]
    idx = raw_data(ctx.input("Indices")).astype(jnp.int32)
    out_hw = ctx.attr("unpooled_size", None)
    if out_hw is None:
        # invert the pooling geometry the layer recorded on this op
        ks = ctx.attr("ksize", [2, 2])
        st = ctx.attr("strides", ks)
        pd = ctx.attr("paddings", [0, 0])
        out_hw = [(x.shape[2] - 1) * int(st[0]) - 2 * int(pd[0])
                  + int(ks[0]),
                  (x.shape[3] - 1) * int(st[1]) - 2 * int(pd[1])
                  + int(ks[1])]
    OH, OW = int(out_hw[0]), int(out_hw[1])
    N, C, h, w = x.shape
    flat = jnp.zeros((N, C, OH * OW), x.dtype)
    # assignment, not accumulation: with overlapping windows a position can
    # win several windows, each carrying the SAME max value — reference
    # math/unpooling.cc writes output[index] = input[i]
    flat = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        idx.reshape(N, C, h * w)].set(x.reshape(N, C, h * w))
    ctx.set_output("Out", flat.reshape(N, C, OH, OW))


# ---------------------------------------------------------------------------
# MD (2-D grid) LSTM — reference: gserver/layers/MDLstmLayer.cpp: an LSTM
# over a 2-D grid where each cell sees hidden/cell state from BOTH the left
# and the up neighbor. Lowered as a lax.scan over rows whose body is a
# lax.scan over columns — XLA sees two nested static loops.

@register_op("mdlstm")
def mdlstm(ctx):
    x = raw_data(ctx.input("X"))                         # [N, H, W, C]
    wx = raw_data(ctx.input("WeightX"))                  # [C, 5*D]
    wl = raw_data(ctx.input("WeightL"))                  # [D, 5*D]
    wu = raw_data(ctx.input("WeightU"))                  # [D, 5*D]
    b = ctx.input("Bias")
    D = wl.shape[0]
    N, H, W, C = x.shape
    pre = jnp.einsum("nhwc,cd->nhwd", x, wx)
    if b is not None:
        pre = pre + raw_data(b).reshape(1, 1, 1, -1)

    def row_step(carry_row, pre_row):
        # carry_row: hidden/cell of the row above: [N, W, D] each
        h_up, c_up = carry_row

        def col_step(carry_col, col_in):
            h_left, c_left = carry_col                   # [N, D]
            pre_t, h_upc, c_upc = col_in                 # [N,5D],[N,D],[N,D]
            g = pre_t + jnp.dot(h_left, wl) + jnp.dot(h_upc, wu)
            i, f_l, f_u, o, cand = jnp.split(g, 5, axis=1)
            i = jax.nn.sigmoid(i)
            f_l = jax.nn.sigmoid(f_l)
            f_u = jax.nn.sigmoid(f_u)
            o = jax.nn.sigmoid(o)
            cand = jnp.tanh(cand)
            c = f_l * c_left + f_u * c_upc + i * cand
            h = o * jnp.tanh(c)
            return (h, c), (h, c)

        z = jnp.zeros((N, D), x.dtype)
        (_, _), (hs, cs) = jax.lax.scan(
            col_step, (z, z),
            (pre_row.swapaxes(0, 1), h_up.swapaxes(0, 1),
             c_up.swapaxes(0, 1)))
        hs = hs.swapaxes(0, 1)                           # [N, W, D]
        cs = cs.swapaxes(0, 1)
        return (hs, cs), hs

    z_row = jnp.zeros((N, W, D), x.dtype)
    (_, _), out = jax.lax.scan(row_step, (z_row, z_row),
                               pre.swapaxes(0, 1))       # scan over H
    ctx.set_output("Out", out.swapaxes(0, 1))            # [N, H, W, D]


# ---------------------------------------------------------------------------
# log-uniform (Zipfian) negative sampler — reference: operators/math/
# sampler.h LogUniformSampler: P(k) = log((k+2)/(k+1)) / log(range+1).

@register_op("log_uniform_random_int", no_gradient=True)
def log_uniform_random_int(ctx):
    shape = [int(d) for d in ctx.attr("shape")]
    rng_range = int(ctx.attr("range"))
    key = ctx.next_rng()
    u = jax.random.uniform(key, tuple(shape))
    # inverse CDF: k = floor(exp(u * log(range+1))) - 1
    k = jnp.exp(u * math.log(rng_range + 1.0)) - 1.0
    out = jnp.clip(k.astype(jnp.int64), 0, rng_range - 1)
    ctx.set_output("Out", out)


def log_uniform_prob(samples, rng_range):
    """log P(k) under the log-uniform sampler (for NCE/IS corrections)."""
    k = samples.astype(jnp.float32)
    return jnp.log(jnp.log((k + 2.0) / (k + 1.0))
                   / math.log(rng_range + 1.0))


@register_op("custom_dist_random_int", no_gradient=True)
def custom_dist_random_int(ctx):
    """Inverse-CDF sampling from a user categorical distribution.
    reference: operators/math/sampler.h CustomSampler (alias table role)."""
    shape = [int(d) for d in ctx.attr("shape")]
    probs = raw_data(ctx.input("Probs")).reshape(-1)
    key = ctx.next_rng()
    cdf = jnp.cumsum(probs / jnp.sum(probs))
    u = jax.random.uniform(key, tuple(shape))
    out = jnp.searchsorted(cdf, u).astype(jnp.int64)
    ctx.set_output("Out", jnp.clip(out, 0, probs.shape[0] - 1))


@register_op("bilinear_interp")
def bilinear_interp(ctx):
    """Bilinear resize of [N,C,H,W] feature maps with the reference's
    align-corners ratio (reference: operators/bilinear_interp_op.cc,
    gserver/layers/BilinearInterpLayer.cpp)."""
    x = raw_data(ctx.input("X"))
    oh = int(ctx.attr("out_h"))
    ow = int(ctx.attr("out_w"))
    N, C, H, W = x.shape
    rh = (H - 1.0) / (oh - 1.0) if oh > 1 else 0.0
    rw = (W - 1.0) / (ow - 1.0) if ow > 1 else 0.0
    ys = jnp.arange(oh, dtype=jnp.float32) * rh
    xs = jnp.arange(ow, dtype=jnp.float32) * rw
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = (ys - y0.astype(jnp.float32))[:, None]
    wx = (xs - x0.astype(jnp.float32))[None, :]
    tl = x[:, :, y0[:, None], x0[None, :]]
    tr = x[:, :, y0[:, None], x1[None, :]]
    bl = x[:, :, y1[:, None], x0[None, :]]
    br = x[:, :, y1[:, None], x1[None, :]]
    out = (tl * (1 - wy) * (1 - wx) + tr * (1 - wy) * wx
           + bl * wy * (1 - wx) + br * wy * wx)
    ctx.set_output("Out", out)


@register_op("conv_shift")
def conv_shift(ctx):
    """Circular row-wise correlation: Out[i, j] = sum_k X[i, (j + k - M//2)
    mod N] * Y[i, k] (reference: operators/conv_shift_op.cc,
    gserver/layers/ConvShiftLayer.cpp; Y width M must be odd)."""
    x = raw_data(ctx.input("X"))     # [B, N]
    y = raw_data(ctx.input("Y"))     # [B, M]
    M = y.shape[1]
    if M % 2 != 1:
        raise ValueError(
            "conv_shift: Y width must be odd (got %d) so the kernel has a "
            "center (reference conv_shift_op enforces this)" % M)
    half = M // 2
    out = None
    for k in range(M):
        t = jnp.roll(x, half - k, axis=1) * y[:, k:k + 1]
        out = t if out is None else out + t
    ctx.set_output("Out", out)
